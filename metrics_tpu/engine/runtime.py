"""StreamingEngine — async micro-batched, multi-tenant metric serving runtime.

The pure-functional core (``Metric.update_state`` / ``compute_from`` /
``merge_states``) is the substrate: state is an explicit pytree, updates are pure and
jittable, so a serving process does not have to serialize clients through a lock or
pay one dispatch per request. Instead:

    client threads ── submit(key, *arrays) ──► bounded queue ──► dispatcher thread
        │                                         │ coalesce + shape-bucket (bucketing.py)
        │  Future (receipt)  ◄─────────────────── │ ONE jitted donated-buffer dispatch
        │                                         ▼ per bucket: masked scan over rows,
        └── compute(key) ◄── flush ── keyed stacked state (stream.py), all tenants

Dispatch semantics are **per-row streaming updates in submission order**: the bucket
kernel scans the coalesced rows, applying the metric's own ``update_state`` to each
tenant's slice and masking padded rows back to their pre-update state. For the
engine's supported metric class (fixed-shape array states — every sum/count/extreme
accumulator) this is exactly the sequential per-request semantics, bit-for-bit; the
compile cache is bounded by ``len(buckets) × log2(tenant capacity)`` kernels PER
request signature (trailing shape + canonical dtype — a serving deployment has a
small fixed set of these; dtypes are canonicalized so numpy/jnp clients share
kernels).

Degradation ladder (each step is correctness-preserving, only slower):

1. fused micro-batched dispatch (the hot path);
2. metrics whose update cannot trace (ragged "cat" states, host-compute, data-dependent
   Python) demote permanently to eager per-request ``update_state`` on the dispatcher
   thread — still async, still multi-tenant;
3. if the dispatcher thread itself dies, the engine completes its in-flight work
   synchronously and every later ``submit`` runs inline on the caller's thread
   (per-call dispatch) — no request is ever silently lost.

Backpressure at a full queue follows ``policy``: ``"block"`` (wait for space),
``"drop"`` (raise :class:`EngineBackpressure` immediately), ``"timeout"`` (wait up to
``submit_timeout`` seconds, then raise).

Overload and abuse protection is the guard plane (``guard=GuardConfig(...)``,
:mod:`metrics_tpu.guard`): per-tenant token-bucket admission, weighted fair
drain forming, request deadlines + CoDel-style shedding, circuit breakers
around compiles/checkpoints/comm sync, poison-tenant quarantine, and a
dispatch watchdog that supersedes a hung worker (inline replay + restart when
the dispatch lock is free; engine quarantine when the device itself is
wedged). ``engine.health()`` exposes the resulting SERVING → DEGRADED →
QUARANTINED state machine. See docs/source/robustness.md.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
import time
import warnings
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Callable, Dict, Hashable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from metrics_tpu.ckpt import format as ckpt_format
from metrics_tpu.ckpt.store import RequestJournal, SnapshotStore
from metrics_tpu.ckpt.writer import AsyncCheckpointer
from metrics_tpu.collections import MetricCollection
from metrics_tpu.engine.bucketing import (
    DEFAULT_BUCKETS,
    BucketConfig,
    Signature,
    choose_bucket,
    inspect_request,
    normalize_buckets,
    pad_micro_batch,
    split_rows,
)
from metrics_tpu.engine.stream import EagerKeyedState, KeyedState
from metrics_tpu.engine.telemetry import EngineTelemetry
from metrics_tpu.guard.config import GuardConfig
from metrics_tpu.guard.errors import EngineQuarantined, TenantQuarantined
from metrics_tpu.guard.plane import GuardPlane
from metrics_tpu.guard.watchdog import HangDetector, Watchdog
from metrics_tpu.metric import Metric
from metrics_tpu.obs import context as _obs_ctx
from metrics_tpu.obs import instrument as _obs
from metrics_tpu.obs.context import TraceContext as _TraceContext
from metrics_tpu.obs.flight import FLIGHT as _FLIGHT
from metrics_tpu.obs.registry import OBS as _OBS
from metrics_tpu.obs.trace import TRACER as _TRACER
from metrics_tpu.parallel.sync import sync_state_host
from metrics_tpu.repl.config import ReplConfig, ReplicaLag
from metrics_tpu.repl.errors import (
    NotPrimaryError,
    NotPromotableError,
    StalenessExceeded,
)
from metrics_tpu.repl.replica import ReplicaApplier
from metrics_tpu.repl.shipper import Shipper
from metrics_tpu.tier.config import TierConfig
from metrics_tpu.tier.residency import (
    HOT,
    TierManager,
    capture_entry,
    peek_state,
    restore_entry,
)
from metrics_tpu.utils.exceptions import MetricsTPUUserError

_POLICIES = ("block", "drop", "timeout")
_WAL_FLUSH = ("none", "flush", "fsync")
_WAL_FSYNC = ("never", "commit", "interval")

# WAL record encoding. Two record types, hand-rolled rather than pickled
# because encoding rides the dispatcher's critical path and per-request
# np.ndarray pickling alone would blow the <5% checkpoint-overhead gate:
#
# - b"C" CHUNK records — the fused hot path. One record per dispatched
#   micro-batch holding the PADDED columns + key_ids + mask exactly as the
#   kernel saw them, plus pickled key mappings for any slot ids this journal
#   has not introduced yet. Cost is a handful of ``tobytes`` calls per up-to-
#   256-row chunk (<0.1µs/request); replay walks the masked rows in scan
#   order, reproducing the kernel's per-row accumulation bit-for-bit.
# - b"R" REQUEST records — eager metrics, degraded/inline submits, and the
#   eager retry after a fused trace failure: pickled key + raw
#   dtype/shape/bytes per arg, applied whole-request on replay (matching how
#   those paths applied it originally).
# - b"Z" RESET / b"W" ROTATE records — single-byte markers for the two state
#   transitions that are not submits: without them a recovery (or a follower)
#   would replay post-reset/post-rotation requests onto pre-transition state
#   and silently diverge from the engine that journaled them.
# - b"D" DEMOTE / b"T" RETIRE / b"P" PROMOTE records — the tier plane's
#   residency transitions. Slot ids in chunk records are only meaningful
#   relative to the retire/reuse history, so every transition that frees or
#   re-fills a slot is journaled IN ORDER with the chunks around it:
#   D (slot + key) demotes a tenant out of the slab (replay re-captures the
#   row from the replayed slab — bit-identical by construction — and parks it
#   in the warm mirror); T (slot + key) retires a tenant entirely; P (slot +
#   key + embedded MTCKPT1 entry blob) readmits one — the blob makes replay
#   independent of the cold spill file's lifetime, so the live engine may
#   delete the file the moment the P record is journaled.

_WAL_U32 = struct.Struct("<I")


def _enc_array(parts: List[bytes], a: np.ndarray) -> None:
    if a.dtype.byteorder == ">":
        a = a.astype(a.dtype.newbyteorder("="))
    name = a.dtype.name.encode()
    parts.append(bytes((len(name), a.ndim)))
    parts.append(name)
    if a.ndim:
        parts.append(struct.pack(f"<{a.ndim}q", *a.shape))
    parts.append(a.tobytes())


def _dec_array(payload: bytes, off: int) -> Tuple[np.ndarray, int]:
    from metrics_tpu.ckpt.format import _dtype_from_name

    nlen, ndim = payload[off], payload[off + 1]
    off += 2
    dtype = _dtype_from_name(payload[off : off + nlen].decode())
    off += nlen
    shape = struct.unpack_from(f"<{ndim}q", payload, off) if ndim else ()
    off += 8 * ndim
    count = int(np.prod(shape)) if ndim else 1
    arr = np.frombuffer(payload, dtype, count, off).reshape(shape)
    return arr, off + count * dtype.itemsize


def _encode_request_record(
    key_bytes: bytes, args: Tuple[Any, ...], ctx: Optional[_TraceContext] = None
) -> bytes:
    parts = [b"R", _WAL_U32.pack(len(key_bytes)), key_bytes, bytes((len(args),))]
    for a in args:
        _enc_array(parts, np.asarray(a))
    if ctx is not None:
        # optional trace-context trailer: fixed 17 bytes after the positional
        # body. Decoders test remaining length, so pre-tracing records (and
        # obs-off writers) replay unchanged — the cross-host propagation
        # carrier for WAL replay and repl shipment.
        parts.append(ctx.to_bytes())
    return b"".join(parts)


def _decode_request_record(
    payload: bytes,
) -> Tuple[Hashable, Tuple[Any, ...], Optional[_TraceContext]]:
    (klen,) = _WAL_U32.unpack_from(payload, 1)
    off = 1 + _WAL_U32.size + klen
    key = pickle.loads(payload[1 + _WAL_U32.size : off])
    nargs = payload[off]
    off += 1
    args = []
    for _ in range(nargs):
        arr, off = _dec_array(payload, off)
        args.append(arr)
    ctx = (
        _TraceContext.from_bytes(payload, off)
        if off + _obs_ctx.WIRE_SIZE <= len(payload)
        else None
    )
    return key, tuple(args), ctx


def _encode_chunk_record(
    new_slots: List[Tuple[int, bytes]],
    key_ids: np.ndarray,
    mask: np.ndarray,
    columns: Sequence[np.ndarray],
    ctxs: Sequence[_TraceContext] = (),
) -> bytes:
    parts = [b"C", struct.pack("<H", len(new_slots))]
    for slot, key_bytes in new_slots:
        parts.append(_WAL_U32.pack(slot))
        parts.append(_WAL_U32.pack(len(key_bytes)))
        parts.append(key_bytes)
    parts.append(bytes((len(columns),)))
    _enc_array(parts, key_ids)
    _enc_array(parts, mask)
    for col in columns:
        _enc_array(parts, col)
    # optional trailer: one wire block per request the chunk coalesced (same
    # remaining-length convention as request records)
    for ctx in ctxs:
        parts.append(ctx.to_bytes())
    return b"".join(parts)


def _record_trace_hexes(payload: bytes) -> str:
    """Comma-joined trace ids from a WAL record's optional trace trailer.

    Re-walks the positional structure with offset arithmetic only (zero-copy
    ``frombuffer`` views, nothing materialised) to find where the trailer
    starts; records without one — pre-tracing journals, obs-off writers,
    non-request kinds — yield ``""``.
    """
    kind = payload[:1]
    try:
        if kind == b"R":
            (klen,) = _WAL_U32.unpack_from(payload, 1)
            off = 1 + _WAL_U32.size + klen
            nargs = payload[off]
            off += 1
            for _ in range(nargs):
                _, off = _dec_array(payload, off)
        elif kind == b"C":
            (n_new,) = struct.unpack_from("<H", payload, 1)
            off = 3
            for _ in range(n_new):
                off += _WAL_U32.size
                (klen,) = _WAL_U32.unpack_from(payload, off)
                off += _WAL_U32.size + klen
            ncols = payload[off]
            off += 1
            for _ in range(2 + ncols):  # key_ids, mask, columns
                _, off = _dec_array(payload, off)
        else:
            return ""
        return ",".join(c.trace_hex for c in _obs_ctx.iter_wire_blocks(payload, off))
    except Exception:  # noqa: BLE001 — attribution is best-effort; replay decides validity
        return ""


def _encode_tier_record(kind: bytes, slot: int, key_bytes: bytes, blob: bytes = b"") -> bytes:
    """One residency-transition WAL record (kind is b"D" / b"T" / b"P").

    ``blob`` rides only on promote records: the readmitted entry as an
    ``MTCKPT1`` container (empty for a cold-registered tenant that never had
    state — replay then installs a fresh init row)."""
    parts = [kind, _WAL_U32.pack(slot), _WAL_U32.pack(len(key_bytes)), key_bytes]
    if kind == b"P":
        parts.append(_WAL_U32.pack(len(blob)))
        parts.append(blob)
    return b"".join(parts)


def _decode_tier_record(payload: bytes) -> Tuple[int, Hashable, Optional[bytes]]:
    (slot,) = _WAL_U32.unpack_from(payload, 1)
    (klen,) = _WAL_U32.unpack_from(payload, 5)
    off = 9
    key = pickle.loads(payload[off : off + klen])
    off += klen
    blob: Optional[bytes] = None
    if payload[:1] == b"P":
        (blen,) = _WAL_U32.unpack_from(payload, off)
        off += 4
        blob = payload[off : off + blen]
    return slot, key, blob


# Engine snapshot payload schema. Engine snapshots are operational (serving
# continuity), not archival: a version bump invalidates old generations — the
# recovery scan just skips them — rather than migrating them.
_ENGINE_SCHEMA_VERSION = 1


@dataclass(frozen=True)
class CheckpointConfig:
    """Durable-state-plane wiring for one :class:`StreamingEngine`.

    ``directory`` holds the generational snapshots AND the WAL segments. A
    background :class:`~metrics_tpu.ckpt.writer.AsyncCheckpointer` persists the
    full multi-tenant state every ``interval_s`` seconds (the dispatcher hands
    it a consistent host view between micro-batches — the submit hot path never
    blocks on IO). The WAL journals each committed fused micro-batch as ONE
    chunk record (padded columns + key ids + mask, journaled after the kernel
    commit and before the chunk's futures resolve) and each eager/inline
    request individually, so a restart recovers the newest valid snapshot and
    replays exactly the work acknowledged after it, in the original per-row
    order (see ``docs/source/persistence.md`` for the exactly-once argument).
    ``policy=None`` keeps snapshots lossless.

    ``wal_flush``: per-drained-batch durability of the journal — ``"none"``
    (OS-buffered; flushed at rotation/close), ``"flush"`` (python-level flush,
    the default), ``"fsync"`` (fsync per batch — strongest, slowest).

    ``wal_fsync``: an orthogonal fsync *policy* on top of ``wal_flush`` —
    ``"never"`` (the default: fsync only where ``wal_flush``/rotation/close
    already do), ``"commit"`` (fsync after every journal append: a committed
    record survives power loss, not just process death), or ``"interval"``
    (fsync at most every ``wal_fsync_interval_s`` seconds: bounds the
    power-loss window without paying a sync per batch). ``wal_flush="fsync"``
    already implies per-batch fsync, so it subsumes both.
    """

    directory: str
    interval_s: float = 30.0
    retain: int = 3
    policy: Optional[Any] = None  # comm.CodecPolicy; None = lossless
    wal: bool = True
    wal_flush: str = "flush"
    wal_fsync: str = "never"
    wal_fsync_interval_s: float = 0.5
    resume: bool = True
    durable: bool = True
    rank: int = 0
    world: int = 1


class EngineClosed(MetricsTPUUserError):
    """submit() after close()."""


class EngineBackpressure(MetricsTPUUserError):
    """Request rejected at a full queue (drop policy, or timeout policy expiry)."""


class _FusedUnsupported(Exception):
    """Internal: the metric's update cannot trace inside the bucket kernel."""


class _WorkerSuperseded(BaseException):
    """Internal: this dispatcher generation was taken over by the hang handler
    mid-batch — retire without touching shared accounting (BaseException so
    per-request isolation never absorbs it)."""


class _Request:
    __slots__ = ("key", "slot", "args", "rows", "signature", "future", "t_submit",
                 "rows_done", "seq", "deadline", "priority", "t_enqueue", "is_probe",
                 "ctx", "t_admitted", "t_drain")

    def __init__(self, key: Hashable, slot: Optional[int], args: Tuple[Any, ...],
                 rows: int, signature: Signature, future: "Future", t_submit: float,
                 deadline: Optional[float] = None, priority: int = 0,
                 t_enqueue: float = 0.0, is_probe: bool = False,
                 ctx: Optional[_TraceContext] = None, t_admitted: float = 0.0) -> None:
        self.key = key
        self.slot = slot
        self.args = args
        self.rows = rows
        self.signature = signature
        self.future = future
        # stamped at submit() ENTRY, before any backpressure wait — the latency
        # percentiles must include the stall they exist to surface
        self.t_submit = t_submit
        # rows already committed to the state (fused chunks commit incrementally, so a
        # mid-batch fused→eager demotion must not re-apply them)
        self.rows_done = 0
        # WAL sequence number once journaled (None while checkpointing is off
        # or the record hasn't been appended yet) — the double-journal guard
        self.seq: Optional[int] = None
        # guard plane: absolute deadline + shed priority on the guard clock,
        # the enqueue stamp sojourn-time shedding reads, and whether this
        # request is a quarantined tenant's single half-open probe (a probe
        # rejected in-queue must free its slot, not wedge the tenant)
        self.deadline = deadline
        self.priority = priority
        self.t_enqueue = t_enqueue
        self.is_probe = is_probe
        # obs plane: the cross-host trace context minted (or adopted) at
        # submit, plus the segment stamps the per-request span is assembled
        # from at resolution time. None/0.0 with obs off — the hot path pays
        # two extra slot writes, no calls.
        self.ctx = ctx
        self.t_admitted = t_admitted
        self.t_drain = 0.0


def _component_metrics(metric: Any) -> List[Metric]:
    if isinstance(metric, MetricCollection):
        return list(metric._modules.values())
    return [metric]


class StreamingEngine:
    """Serve a ``Metric`` or ``MetricCollection`` to many concurrent clients.

    Args:
        metric_or_collection: the logical metric. The engine works on a private clone,
            so the caller's instance stays free for direct use.
        buckets: micro-batch row sizes the kernels compile for — a sequence or a
            :class:`~metrics_tpu.engine.bucketing.BucketConfig` (powers of two by
            default; ``BucketConfig(ladder=tune_buckets(trace))`` installs a
            ladder autotuned from measured occupancy). The compile cache after
            warmup is bounded by this set.
        max_queue: bound on queued (not yet dispatched) requests.
        policy: backpressure policy at a full queue — "block" | "drop" | "timeout".
        submit_timeout: seconds a "timeout"-policy submit waits for queue space.
        window: sliding-window length in segments (see :meth:`rotate_window`);
            ``None`` disables windowing.
        capacity: initial tenant capacity (rounded up to a power of two; grows by
            doubling as keys arrive — each growth recompiles the bucket kernels once).
        start: launch the dispatcher thread immediately.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryAccuracy
        >>> from metrics_tpu.engine import StreamingEngine
        >>> engine = StreamingEngine(BinaryAccuracy(), buckets=(4, 8))
        >>> for preds, target in [([1, 0], [1, 1]), ([1], [1])]:
        ...     fut = engine.submit("tenant-a", jnp.array(preds), jnp.array(target))
        >>> engine.flush()
        >>> engine.compute("tenant-a")
        Array(0.6666667, dtype=float32)
        >>> engine.close()
    """

    def __init__(
        self,
        metric_or_collection: Any,
        *,
        buckets: Union[Sequence[int], BucketConfig] = DEFAULT_BUCKETS,
        max_queue: int = 1024,
        policy: str = "block",
        submit_timeout: float = 1.0,
        window: Optional[int] = None,
        capacity: int = 8,
        telemetry_window: int = 2048,
        checkpoint: Optional[CheckpointConfig] = None,
        guard: Optional[GuardConfig] = None,
        replication: Optional[ReplConfig] = None,
        tier: Optional[TierConfig] = None,
        device: Optional[Any] = None,
        telemetry_labels: Optional[Dict[str, str]] = None,
        start: bool = True,
    ) -> None:
        if not isinstance(metric_or_collection, (Metric, MetricCollection)):
            raise MetricsTPUUserError(
                f"StreamingEngine serves a Metric or MetricCollection, got {type(metric_or_collection)!r}"
            )
        if policy not in _POLICIES:
            raise MetricsTPUUserError(f"`policy` must be one of {_POLICIES}, got {policy!r}")
        if max_queue < 1:
            raise MetricsTPUUserError(f"`max_queue` must be >= 1, got {max_queue}")

        self._metric = metric_or_collection.clone()
        # reads get their OWN clone: compute_from swaps state attrs in and out
        # of its instance, so computing on the dispatch metric would serialize
        # every read behind dispatch/replay on the dispatch lock. With a read
        # clone, compute() only needs that lock for the state slice (an
        # enqueue-only pytree gather), and readers serialize among themselves
        # on _read_lock — the follower read-throughput gate rides on this.
        self._read_metric = self._metric.clone()
        self._read_lock = threading.Lock()
        # jitted fused read path: slice + compute_from as ONE compiled call
        # (slot is a traced operand — one kernel per capacity serves every
        # tenant). Its closure gets a third clone: compute_from swaps attrs at
        # trace time, and tracing (dispatch lock) must not race an eager
        # reader (_read_lock). Falls back permanently on the first trace
        # failure (host-compute/untraceable computes read eagerly).
        self._read_jit_metric = self._metric.clone()
        self._read_kernels: Dict[int, Callable] = {}
        # serializes first-read trace+compile per capacity (compute_from swaps
        # attrs on _read_jit_metric at trace time — two cold readers must not
        # trace concurrently). Taken OFF the dispatch lock: a read compile must
        # never stall dispatch (primary) or WAL replay (follower).
        self._read_compile_lock = threading.Lock()
        self._read_jit_ok = True
        # serializes sync=True collective syncs: two readers syncing different
        # tenants concurrently would issue cross-process collectives in
        # whatever order their threads race to — ranks disagreeing on that
        # order deadlocks (or cross-wires) the job. Dispatch used to provide
        # this ordering incidentally when compute() synced under its lock.
        self._sync_state_lock = threading.Lock()
        self._buckets = normalize_buckets(buckets)
        self._max_rows = self._buckets[-1]
        self._max_queue = int(max_queue)
        self._policy = policy
        self._submit_timeout = float(submit_timeout)
        self.telemetry = EngineTelemetry(
            latency_window=telemetry_window, labels=telemetry_labels
        )
        # shard planes label their engines; the slab-bytes gauge carries it
        self._shard_label = str((telemetry_labels or {}).get("shard", ""))
        # optional device pin: every stacked leaf is committed here, so jit
        # dispatches follow it — the shard plane places one engine per mesh
        # device to get true multi-device parallelism
        self._device = device

        # Fused eligibility is structural: every component metric must hold only
        # fixed-shape array states (ragged "cat" lists cannot stack along a key axis)
        # and compute on device. Untraceable *updates* are only discoverable at trace
        # time — those demote at the first kernel build instead (telemetry
        # `fused_fallbacks`).
        self._fused = all(
            not m._host_compute and not any(isinstance(d, list) for d in m._defaults.values())
            for m in _component_metrics(self._metric)
        )
        self._keyed = (
            KeyedState(self._metric, capacity=capacity, window=window, device=device)
            if self._fused
            else EagerKeyedState(self._metric, window=window)
        )
        self._window = window

        # tier plane (metrics_tpu.tier): residency-aware HBM/host-RAM/disk
        # state tiering. None-checked on every hot path — an untiered engine
        # pays one attribute test per drained batch and nothing per request.
        # _tier_policy distinguishes a user-configured tier (eviction pass
        # runs) from one materialised lazily by replay/restore of residency
        # records (mechanics only: state is kept readmittable, nothing is
        # proactively demoted until the operator configures a policy).
        self._tier: Optional[TierManager] = (
            TierManager(tier, self._metric) if tier is not None else None
        )
        self._tier_policy = tier is not None

        # (signature, bucket, capacity) -> jitted kernel
        self._kernels: Dict[Tuple[Signature, int, int], Callable] = {}

        self._lock = threading.RLock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._idle = threading.Condition(self._lock)
        self._queue: List[_Request] = []
        self._inflight = 0
        self._closed = False
        self._degraded = False
        self._quarantined = False  # hung worker wedged in a device call: fail fast
        self._worker_error: Optional[BaseException] = None
        # dispatcher generations: the hang handler supersedes a worker by
        # bumping the epoch; a worker re-validates its epoch at every shared-
        # state touchpoint and retires silently when stale
        self._worker_epoch = 0
        self._active_batch: Optional[List[_Request]] = None
        self._worker_restarts = 0
        self._zombie_workers = 0
        # serializes use of the private metric instance (update_state/compute_from
        # swap state attrs in and out, so two threads must not interleave there)
        self._dispatch_lock = threading.Lock()
        # test/ops hook: clearing holds the dispatcher *before* it processes a drained
        # batch, letting backpressure be exercised deterministically
        self._worker_gate = threading.Event()
        self._worker_gate.set()

        # durable state plane (None-checked on every hot path: checkpointing
        # off costs one attribute test per drained batch)
        self._ckpt_cfg: Optional[CheckpointConfig] = None
        self._ckpt_store: Optional[SnapshotStore] = None
        self._ckpt_writer: Optional[AsyncCheckpointer] = None
        self._journal: Optional[RequestJournal] = None
        self._wal_seq = -1
        self._wal_error: Optional[BaseException] = None
        self._wal_key_cache: Dict[Hashable, bytes] = {}
        self._wal_slots_sent: set = set()  # slot ids already introduced to the journal
        self._replay_slot_keys: Dict[int, Hashable] = {}
        self._snapshot_seqs: Dict[int, int] = {}  # generation -> WAL seq it covers
        # guard plane (None-checked on every hot path, like checkpointing)
        self._guard: Optional[GuardPlane] = None
        self._hang_detector: Optional[HangDetector] = None
        self._watchdog: Optional[Watchdog] = None
        if guard is not None:
            self._guard = GuardPlane(guard, telemetry=self.telemetry, max_rows=self._max_rows)
            if guard.watchdog_timeout_s is not None:
                self._hang_detector = HangDetector(guard.watchdog_timeout_s, clock=guard.clock)
                self._watchdog = Watchdog(
                    self._hang_detector.hung, self._on_worker_hang, poll_s=guard.watchdog_poll_s
                )

        # replication plane (metrics_tpu.repl): primary ships its snapshot+WAL
        # lineage off-thread; a follower is a read replica that replays it
        self._repl_cfg: Optional[ReplConfig] = None
        self._shipper: Optional[Shipper] = None
        self._applier: Optional[ReplicaApplier] = None
        self._repl_follower = False
        self._repl_epoch = 0
        self._promote_lock = threading.Lock()
        # cluster plane (metrics_tpu.cluster): the supervising ClusterNode
        # registers itself here so health() can carry a `cluster` section
        self._cluster: Optional[Any] = None
        # health-transition tracking (guard on_health_transition hook)
        self._last_health_state = "SERVING"

        if replication is not None and replication.role == "follower" and checkpoint is not None:
            raise MetricsTPUUserError(
                "a follower replica does not own a durable lineage while following — its state "
                "is the primary's, re-bootstrappable from the ship link. Configure the lineage "
                "it should open AT PROMOTION via ReplConfig(promote_checkpoint=CheckpointConfig(...))"
            )
        if checkpoint is not None:
            self._init_checkpoint(checkpoint)
        if replication is not None:
            self._init_replication(replication)

        # flight-recorder context provider: dump() may run while a trigger
        # site holds guard/engine locks, so this reads bare attributes only —
        # NEVER health() (which takes self._lock and could deadlock the dump)
        self._flight_provider_name = f"engine:{self.telemetry.engine_id}"
        _FLIGHT.register_provider(self._flight_provider_name, self._flight_context)

        self._worker: Optional[threading.Thread] = None
        if start and not self._repl_follower:
            # a follower has no dispatcher: the applier thread owns its state
            # until promote() flips it writable (which starts one)
            self.start()

    # ------------------------------------------------------------------ lifecycle

    def start(self) -> None:
        with self._lock:
            if self._worker is not None or self._closed:
                return
            self._spawn_worker()

    def _spawn_worker(self) -> None:
        """Start a dispatcher thread for the CURRENT epoch (caller holds the lock)."""
        self._worker = threading.Thread(
            target=self._run,
            args=(self._worker_epoch,),
            name="metrics-tpu-engine-dispatch",
            daemon=True,
        )
        self._worker.start()

    def close(self, flush: bool = True, checkpoint: bool = True) -> None:
        """Stop accepting work; by default drain what was already accepted.

        With checkpointing configured, a final snapshot is committed after the
        drain (``checkpoint=False`` skips it — the crash-simulation hook: the
        WAL then carries everything since the last periodic snapshot, exactly
        what a restart must replay).
        """
        with self._lock:
            if self._closed:
                return
        if flush and not self._quarantined:
            self.flush()
        if flush and checkpoint and self._ckpt_writer is not None and not self._quarantined:
            # a quarantined engine's dispatch lock may be held by the wedged
            # worker forever — taking a final snapshot would hang close()
            self._ckpt_writer.checkpoint_sync(self._checkpoint_view)
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()
            self._idle.notify_all()
            worker = self._worker
        if self._watchdog is not None:
            self._watchdog.stop()
        if self._shipper is not None:
            self._shipper.close()
        if self._applier is not None:
            self._applier.stop()
        if worker is not None and worker is not threading.current_thread():
            worker.join(timeout=10.0)
            if worker.is_alive():
                # the dispatcher outlived its join: surface the zombie instead
                # of returning as if the engine closed cleanly — it may still
                # hold the dispatch lock or a device, and health() says so
                self._zombie_workers += 1
                self.telemetry.count("zombie_workers")
                warnings.warn(
                    "StreamingEngine.close(): dispatcher thread did not exit within "
                    "10s and is now a zombie (possibly wedged in a device call); "
                    "engine health is DEGRADED, state may be incomplete",
                    RuntimeWarning,
                    stacklevel=2,
                )
        self._publish_health()
        _FLIGHT.unregister_provider(self._flight_provider_name)
        if self._ckpt_writer is not None:
            self._ckpt_writer.close()
        if self._journal is not None:
            self._journal.close()

    def _flight_context(self) -> Dict[str, Any]:
        """Post-mortem context for flight-recorder bundles.

        Lock-free by contract: bundles are dumped synchronously at trigger
        sites that may already hold guard/engine locks, so everything here is
        a bare attribute read (int/bool/len on a list reference) — slightly
        racy values beat a deadlocked dump.
        """
        return {
            "engine": self.telemetry.engine_id,
            "wal_seq": self._wal_seq,
            "health_state": self._last_health_state,
            "queue_depth": len(self._queue),
            "worker_restarts": self._worker_restarts,
            "zombie_workers": self._zombie_workers,
            "degraded": self._degraded,
            "quarantined": self._quarantined,
            "closed": self._closed,
            "repl_follower": self._repl_follower,
            "repl_epoch": self._repl_epoch,
        }

    def __enter__(self) -> "StreamingEngine":
        return self

    def __exit__(self, *exc_info: Any) -> None:
        self.close()

    # ------------------------------------------------------------------ client API

    def submit(
        self,
        key: Hashable,
        *args: Any,
        deadline: Optional[float] = None,
        priority: int = 0,
    ) -> "Future":
        """Enqueue one update for tenant ``key``; resolves to a receipt dict once the
        state update has committed.

        Raises :class:`EngineBackpressure` per the configured policy when the queue is
        full, and :class:`EngineClosed` after :meth:`close`. With a guard plane
        configured (``guard=GuardConfig(...)``): ``deadline`` (seconds from now) makes
        the request fail fast with :class:`~metrics_tpu.guard.errors.DeadlineExceeded`
        if it expires before dispatch; ``priority`` orders overload shedding (requests
        at or below the configured shed priority are droppable under standing
        overload); quota-exhausted and quarantined tenants are rejected at entry
        (:class:`~metrics_tpu.guard.errors.QuotaExceeded` /
        :class:`~metrics_tpu.guard.errors.TenantQuarantined`); a quarantined *engine*
        (wedged device) rejects everything with
        :class:`~metrics_tpu.guard.errors.EngineQuarantined`.
        """
        if self._repl_follower:
            raise NotPrimaryError(
                "submit() on a follower replica: writes go to the primary; this engine serves "
                "bounded-staleness reads until promote() flips it writable"
            )
        t_submit = time.perf_counter()
        # trace context: adopt the ambient one (a ShardedEngine delegation or a
        # caller's activate()) or mint a fresh root — obs-off submits carry None
        # after one attribute test
        ctx = _obs_ctx.mint_or_current() if _OBS.enabled else None
        rows, signature = inspect_request(args)
        guard = self._guard
        abs_deadline: Optional[float] = None
        t_enqueue = 0.0
        is_probe = False
        if guard is not None:
            if self._quarantined:
                raise EngineQuarantined(
                    "submit() on a quarantined StreamingEngine (dispatcher wedged in a device call)"
                )
            # full admission only when there is something to check — a guarded
            # submit with no quotas, no deadline and a clean quarantine ledger
            # costs attribute loads, not calls (the guard <5% overhead gate)
            if deadline is not None or guard.admission_active or guard._quarantine_entries:
                abs_deadline, is_probe = guard.admit(key, rows, deadline)
            if guard.stamp_enqueue:
                # the default guard clock IS perf_counter: reuse the entry stamp
                t_enqueue = t_submit if guard.clock is time.perf_counter else guard.clock()
        # admission segment boundary for the traced request (the guard checks
        # above are everything between the two stamps)
        t_admitted = time.perf_counter() if ctx is not None else 0.0
        try:
            future: Future = Future()
            with self._not_full:
                if self._closed:
                    raise EngineClosed("submit() on a closed StreamingEngine")
                if self._quarantined:
                    raise EngineQuarantined(
                        "submit() on a quarantined StreamingEngine (dispatcher wedged in a device call)"
                    )
                if guard is not None and guard.quarantine.is_held(key):
                    # a migration hold landed between admission and here: refuse
                    # SYNCHRONOUSLY, or this row would commit on the source
                    # after the drain barrier exported the tenant — lost state
                    raise TenantQuarantined(
                        f"tenant {key!r} is held (migration in flight); "
                        "reload the partition map and resubmit"
                    )
                if self._degraded or self._worker is None:
                    # synchronous per-call dispatch (dispatcher dead or never started)
                    req = _Request(key, self._alloc_slot(key), tuple(args), rows, signature,
                                   future, t_submit, abs_deadline, priority, t_enqueue, is_probe,
                                   ctx, t_admitted)
                    self.telemetry.count("submitted")
                    self._apply_inline(req)
                    return future
                backlog = guard.backlog if guard is not None else None
                wait_deadline = time.monotonic() + self._submit_timeout
                while len(self._queue) + (backlog.count if backlog is not None else 0) >= self._max_queue:
                    if self._policy == "drop":
                        self.telemetry.count("dropped")
                        raise EngineBackpressure(f"queue full ({self._max_queue}); request dropped")
                    if self._policy == "timeout":
                        remaining = wait_deadline - time.monotonic()
                        if remaining <= 0:
                            self.telemetry.count("timed_out")
                            raise EngineBackpressure(
                                f"queue full ({self._max_queue}); timed out after {self._submit_timeout}s"
                            )
                        self._not_full.wait(remaining)
                    else:
                        self._not_full.wait()
                    if self._closed:
                        raise EngineClosed("StreamingEngine closed while waiting for queue space")
                    if self._quarantined:
                        raise EngineQuarantined(
                            "StreamingEngine quarantined while waiting for queue space"
                        )
                    if self._degraded:
                        req = _Request(key, self._alloc_slot(key), tuple(args), rows, signature,
                                       future, t_submit, abs_deadline, priority, t_enqueue, is_probe,
                                       ctx, t_admitted)
                        self.telemetry.count("submitted")
                        self._apply_inline(req)
                        return future
                if guard is not None and guard.quarantine.is_held(key):
                    # the backpressure wait released the lock — a hold may have
                    # landed while this request sat out a full queue
                    raise TenantQuarantined(
                        f"tenant {key!r} is held (migration in flight); "
                        "reload the partition map and resubmit"
                    )
                req = _Request(key, self._alloc_slot(key), tuple(args), rows, signature,
                               future, t_submit, abs_deadline, priority, t_enqueue, is_probe,
                               ctx, t_admitted)
                self._queue.append(req)
                self.telemetry.count("submitted")
                self.telemetry.gauge_queue_depth(len(self._queue))
                self._not_empty.notify()
            return future
        except Exception:
            if is_probe:
                # the admitted quarantine probe never reached processing:
                # free the probe slot so the tenant is not wedged in probation
                guard.abandon_probe(key)
            raise

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every accepted request has committed (or ``timeout`` elapses).

        Holds through a worker death too: the death handler keeps ``_inflight`` equal
        to the number of accepted-but-unreplayed requests while it replays them
        inline, so 'accepted implies committed after flush' survives degradation.

        Condition-variable wakeups, not polling: every transition that empties the
        queue/in-flight set notifies ``_idle`` (batch completion, worker-death and
        hang-takeover replay, engine quarantine, close), so a waiting flush pays no
        busy-wait tax and wakes the moment the engine is drained.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        backlog = self._guard.backlog if self._guard is not None else None
        with self._idle:
            while (
                self._queue
                or self._inflight
                or (backlog is not None and backlog.count)
            ):
                if deadline is None:
                    self._idle.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError("StreamingEngine.flush timed out")
                    self._idle.wait(remaining)

    def drain_tenant(self, key: Hashable, timeout: Optional[float] = None) -> None:
        """Block until no accepted-but-uncommitted request references ``key``.

        The migration barrier: once the caller holds ``key`` (quarantine hold)
        nothing new for it can be accepted, so waiting out the requests already
        resident in the arrival queue, the guard backlog, and the active batch
        is enough — unlike :meth:`flush`, whose whole-engine barrier never
        clears while neighbouring tenants keep the engine busy, and a live
        migration must not require a quiet engine.

        ``_idle`` only fires on a FULL drain, so this poll-waits on it: a busy
        engine still releases per-tenant waiters within one poll interval.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        backlog = self._guard.backlog if self._guard is not None else None
        with self._idle:
            while True:
                pending = any(req.key == key for req in self._queue)
                if not pending and self._active_batch is not None:
                    pending = any(req.key == key for req in self._active_batch)
                if not pending and backlog is not None and backlog.count:
                    pending = backlog.pending_for(key) > 0
                if not pending and self._inflight and self._active_batch is None:
                    # worker-death / hang-takeover replay: the pending list
                    # lives off-structure and may hold our key — wait it out
                    pending = True
                if not pending:
                    return
                if deadline is not None and time.monotonic() >= deadline:
                    raise TimeoutError(f"drain_tenant({key!r}) timed out")
                self._idle.wait(0.05)

    def compute(self, key: Hashable, *, window: bool = False, sync: bool = False) -> Any:
        """Final metric value for tenant ``key`` (flushes first).

        ``window=True`` computes over the sliding window (requires ``window=`` at
        construction); ``sync=True`` all-reduces the state across JAX processes first
        (multi-host serving), via :func:`metrics_tpu.parallel.sync.sync_state_host`.
        """
        if window and self._window is None:
            # a silent fall-through would return unbounded lifetime accumulation
            # mislabeled as a sliding-window value
            raise MetricsTPUUserError("compute(window=True) requires the engine to be built with `window=`")
        self._check_quarantined("compute")
        self._check_staleness()
        self.flush()
        # dispatch lock covers only the read's enqueue: the warm jitted fused
        # read (slice + compute in one compiled call) or the state slice. Slice
        # ops are enqueued against still-valid buffers (a later kernel donation
        # cannot reach them); sync + eager compute run off-lock on the read
        # clone, and a COLD read's trace+compile runs off-lock on a private
        # buffer copy — reads never wait out a dispatch or a replay, and
        # dispatch never waits out a read compile. This is what lets a read
        # replica serve dashboard traffic at multiples of the primary's read
        # rate (benchmarks/engine_throughput.py --replica).
        cold_read = None
        resident = True
        with self._dispatch_lock:
            keyed = self._keyed
            if not self._is_resident(key):
                tier = self._tier
                if tier is None or not tier.has(key):
                    raise KeyError(f"unknown tenant key {key!r}")
                # non-resident tenant: host-side peek of its warm/cold entry —
                # no readmission, no slab writes. Reads must not change
                # residency (a follower's reads may not mutate state at all,
                # and a dashboard sweep over a million cold tenants must not
                # thrash the hot set). Runs under the dispatch lock so the
                # rotation counter and the entry are read consistently.
                resident = False
                state = peek_state(
                    self._metric, keyed, tier.peek_entry(key) or {}, window=window
                )
            elif (
                not window
                and not sync
                and self._read_jit_ok
                and isinstance(keyed, KeyedState)
                and keyed._slots[key] < keyed.capacity
            ):
                slot = jnp.asarray(keyed._slots[key], jnp.int32)
                kernel = self._read_kernels.get(keyed.capacity)
                if kernel is not None:
                    try:
                        return kernel(keyed.stacked, slot)
                    except Exception as exc:  # noqa: BLE001 — untraceable compute: eager forever
                        self._disable_read_jit(exc)
                else:
                    # first read at this capacity: jax.jit traces + compiles at
                    # call time, which can take 100ms-1s — far too long to hold
                    # the dispatch lock (it would stall every queued write on a
                    # primary and all WAL replay on a follower). Snapshot the
                    # tenant's buffers into private copies (enqueued here, under
                    # the lock, so a later donating dispatch can't invalidate
                    # them — and jnp.copy preserves avals, so the compiled
                    # kernel serves subsequent warm reads of the live buffers)
                    # and pay the compile OFF the lock.
                    cold_read = (
                        jax.tree.map(jnp.copy, keyed.stacked), slot, keyed.capacity
                    )
            if resident and cold_read is None:
                state = keyed.merged_state(key) if window else keyed.state_of(key)
        if cold_read is not None:
            stacked_copy, slot, capacity = cold_read
            try:
                with self._read_compile_lock:
                    kernel = self._read_kernels.get(capacity)
                    if kernel is None:
                        kernel = self._build_read_kernel()
                        out = kernel(stacked_copy, slot)  # trace+compile happens HERE
                        # publish only after the tracing call completes: warm
                        # readers call published kernels without this lock, so
                        # an uncompiled kernel in the dict would let a warm
                        # reader trace concurrently on the shared
                        # _read_jit_metric clone (whose compute_from swaps
                        # attrs at trace time — the race this lock exists for)
                        self._read_kernels[capacity] = kernel
                        return out
                    return kernel(stacked_copy, slot)
            except Exception as exc:  # noqa: BLE001 — untraceable compute: eager forever
                self._disable_read_jit(exc)
                with self._dispatch_lock:
                    state = self._keyed.state_of(key)
        if sync:
            state = self._sync_state(state)
        with self._read_lock:
            return self._read_metric.compute_from(state)

    def _disable_read_jit(self, exc: BaseException) -> None:
        # loudly, not silently: losing the compiled read path costs the replica
        # read-throughput property, and the trigger may be a real bug rather
        # than an untraceable compute
        self._read_jit_ok = False
        self.telemetry.count("read_jit_fallbacks")
        warnings.warn(
            f"StreamingEngine: jitted read path disabled after {exc!r}; "
            "compute() serves eagerly from now on",
            RuntimeWarning,
            stacklevel=3,
        )

    def _build_read_kernel(self) -> Callable:
        """A fresh unpublished jitted read — the caller compiles it (first call)
        under ``_read_compile_lock`` and publishes to ``_read_kernels`` after."""
        metric = self._read_jit_metric

        def read(stacked: Any, slot: jax.Array) -> Any:
            return metric.compute_from(jax.tree.map(lambda x: x[slot], stacked))

        return jax.jit(read)

    def compute_all(self, *, window: bool = False, sync: bool = False) -> Dict[Hashable, Any]:
        """``compute`` for every known tenant key — one flush, one consistent snapshot.

        All tenants' states are read under a single dispatch-lock acquisition after a
        single flush, so under live traffic the returned mapping is a point-in-time
        view (per-key ``compute`` in a loop would re-flush per tenant and interleave
        with new submissions).
        """
        if window and self._window is None:
            raise MetricsTPUUserError("compute_all(window=True) requires the engine to be built with `window=`")
        self._check_quarantined("compute_all")
        self._check_staleness()
        self.flush()
        with self._dispatch_lock:
            states: Dict[Hashable, Any] = {
                key: self._keyed.merged_state(key) if window else self._keyed.state_of(key)
                for key in self._keyed.keys
            }
            tier = self._tier
            if tier is not None:
                # non-resident tenants read host-side, no readmission: a
                # full-fleet sweep must not evict the live hot set to serve it
                for key in tier.keys():
                    if key not in states:
                        states[key] = peek_state(
                            self._metric, self._keyed, tier.peek_entry(key) or {}, window=window
                        )
        out: Dict[Hashable, Any] = {}
        for key, state in states.items():
            if sync:
                state = self._sync_state(state)
            with self._read_lock:
                out[key] = self._read_metric.compute_from(state)
        return out

    def wal_watermark(self) -> Tuple[int, int]:
        """``(epoch, seq)`` — this engine's WAL position, the query plane's cache stamp.

        On a primary this is the last journaled seq in the current lineage
        epoch, read under the promote lock so a concurrent role flip cannot
        tear a (new epoch, old seq) pair. On a follower it is the applier's
        applied position, behind the same bounded-staleness gate as every
        other follower read — a replica too stale to serve a rollup is also
        too stale to vouch for a cached one. ``seq`` is ``-1`` for an engine
        with no journaled write yet (or no durable plane at all), which the
        cache treats as never-valid rather than never-changing.
        """
        if self._closed:
            raise EngineClosed("wal_watermark() on a closed StreamingEngine")
        self._check_quarantined("wal_watermark")
        self._check_staleness()
        applier = self._applier
        if self._repl_follower and applier is not None:
            wm = applier.watermark()
        else:
            with self._promote_lock:
                wm = (int(self._repl_epoch), int(self._wal_seq))
        partition = self.telemetry.label("partition")
        if partition:
            _obs.set_part_wal_seq(self.telemetry.engine_id, partition, wm[1])
        return wm

    def rollup(self, *, window: bool = False) -> Any:
        """Fold EVERY local tenant into one mergeable state, stamped for the cache.

        The global-query read primitive (:mod:`metrics_tpu.query`): one
        watermark-stamped :class:`~metrics_tpu.query.rollup.PartitionRollup`
        per partition replaces a per-tenant scatter. Served by followers too
        (under the staleness gate) — the rollup fold itself never mutates
        state, never changes tier residency, and never touches the write path
        beyond the same flush ``compute`` pays.

        The watermark is read BEFORE the state snapshot: anything journaled
        in between is in the fold but not the stamp, so a cached result can
        only ever UNDER-claim its coverage — revalidation then invalidates
        early, never serves a stamp the state doesn't back. (Reading it after
        would claim seqs the snapshot may lack; nesting the promote lock
        inside the dispatch lock would invert ``promote()``'s order.)
        """
        from metrics_tpu.query.rollup import PartitionRollup, fold_slab, fold_states, merge_folds

        if window and self._window is None:
            raise MetricsTPUUserError("rollup(window=True) requires the engine to be built with `window=`")
        self._check_quarantined("rollup")
        self._check_staleness()
        if self._closed:
            raise EngineClosed("rollup() on a closed StreamingEngine")
        self.flush()
        t0 = time.monotonic()
        watermark = self.wal_watermark()
        slab = None
        ring: List[Tuple[Any, Any]] = []  # (snapshot pytree, live-slot gather index)
        eager: List[Any] = []
        peeked: List[Any] = []
        with self._dispatch_lock:
            keyed = self._keyed
            tenants = len(keyed.keys)
            if isinstance(keyed, KeyedState):
                # refs only, folded off-lock: the slab is functionally replaced
                # by dispatches, so a grabbed ref is an immutable snapshot
                slab = keyed.stacked
                if window and keyed._ring:
                    # gather live slots only: a demoted tenant's ring rows
                    # survive until release_slot scrubs them (see
                    # KeyedState.evict), and its history already lives in its
                    # tier entry — whole-segment folds would double-count it
                    slots = sorted(s for s in keyed._slots.values() if s < keyed.capacity)
                    for cap, snap in keyed._ring:
                        idx = [s for s in slots if s < cap]
                        if idx:
                            ring.append((snap, jnp.asarray(idx, jnp.int32)))
            else:
                eager = [
                    keyed.merged_state(key) if window else keyed.state_of(key)
                    for key in keyed.keys
                ]
            tier = self._tier
            if tier is not None:
                resident = set(keyed.keys)
                for key in tier.keys():
                    if key in resident:
                        continue
                    tenants += 1
                    entry = tier.peek_entry(key)
                    # a registered-but-silent cold tenant has no entry at all;
                    # peek_state would hand back init_state() — the fold
                    # identity — so it counts toward coverage and contributes
                    # nothing. Skipping it keeps a million-registered-tenant
                    # rollup O(tenants with state), not O(registered).
                    if entry:
                        peeked.append(
                            peek_state(self._metric, keyed, entry, window=window)
                        )
        folds: List[Any] = []
        for snap, idx in ring:  # oldest segment first, matching merged_state
            folds.append(fold_slab(self._metric, jax.tree.map(lambda x: x[idx], snap)))
        if slab is not None:
            # free + never-dispatched rows hold init values — the reduction
            # identities — so the whole-slab fold needs no residency mask
            folds.append(fold_slab(self._metric, slab))
        if eager:
            folds.append(fold_states(self._metric, eager))
        if peeked:
            folds.append(fold_states(self._metric, peeked))
        state = merge_folds(self._metric, folds)
        lag = self.replica_lag()
        _obs.record_query_rollup_seconds(self.telemetry.engine_id, time.monotonic() - t0)
        return PartitionRollup(
            partition=self.telemetry.label("partition"),
            state=state,
            watermark=watermark,
            tenants=tenants,
            follower=self._repl_follower,
            node=self.telemetry.engine_id,
            staleness_seqs=None if lag is None else lag.seqs_behind,
            staleness_s=None if lag is None else lag.seconds_behind,
        )

    def _check_quarantined(self, op: str) -> None:
        """Fail fast instead of deadlocking on a dispatch lock a wedged worker holds."""
        if self._quarantined:
            raise EngineQuarantined(
                f"{op}() on a quarantined StreamingEngine (dispatcher wedged in a device call)"
            )

    def rotate_window(self) -> None:
        """Close the current sliding-window segment for ALL tenants (flushes first)."""
        self._check_quarantined("rotate_window")
        self._check_writable("rotate_window")
        self.flush()
        with self._dispatch_lock:
            # journaled INSIDE the lock, before the transition: a recovery or a
            # follower replays it at exactly this point in the request order
            if self._journal is not None:
                self._journal_append([b"W"])
            self._keyed.rotate()
        self.telemetry.count("window_rotations")

    def reset(self) -> None:
        """Drop all tenant state (keys stay allocated)."""
        self._check_quarantined("reset")
        self._check_writable("reset")
        self.flush()
        orphans: List[str] = []
        with self._dispatch_lock:
            if self._journal is not None:
                self._journal_append([b"Z"])
            self._keyed.reset()
            if self._tier is not None:
                # every non-resident tenant becomes cold-with-init; their spill
                # files are orphans once the reset is journaled
                orphans = self._tier.reset()
        if self._tier is not None and self._tier.store is not None:
            for name in orphans:
                self._tier.store.delete(name)

    def _check_writable(self, op: str) -> None:
        if self._repl_follower:
            raise NotPrimaryError(
                f"{op}() on a follower replica: its state mirrors the primary's and is "
                "mutated only by replay (promote() flips this engine writable)"
            )

    @property
    def fused(self) -> bool:
        """True while the engine serves via the single-dispatch bucket kernels."""
        return self._fused

    @property
    def degraded(self) -> bool:
        """True once the dispatcher died and submits run inline."""
        return self._degraded

    @property
    def quarantined(self) -> bool:
        """True once a hung dispatcher could not be safely superseded (device
        wedged): the engine fails fast instead of hanging callers."""
        return self._quarantined

    def health(self) -> Dict[str, Any]:
        """The engine's health state machine, one plain dict.

        ``state`` walks ``SERVING → DEGRADED → QUARANTINED``:

        - ``SERVING`` — nominal (fused or eager, dispatcher alive);
        - ``DEGRADED`` — serving continues with reduced quality: the
          dispatcher died and submits run inline, a circuit breaker is open,
          the overload controller is actively shedding, the WAL was disabled
          after an IO failure, or a zombie worker survived ``close()``;
        - ``QUARANTINED`` — the engine cannot serve safely (a hung worker
          holds the dispatch lock); every call fails fast.

        Mirrored to the master-gated ``metrics_tpu_guard_health_state`` gauge
        whenever it is read or transitions.
        """
        with self._lock:
            quarantined = self._quarantined
            degraded = self._degraded
            zombies = self._zombie_workers
            worker = self._worker
            closed = self._closed
            restarts = self._worker_restarts
            queue_depth = len(self._queue)
            if self._guard is not None:
                queue_depth += self._guard.backlog.count
        guard = self._guard
        breakers = guard.breaker_snapshots() if guard is not None else {}
        shedding = guard.shedding if guard is not None else False
        wal_disabled = self._wal_error is not None
        # a fenced shipper is a deposed primary still serving local writes:
        # split-brain territory — loudly DEGRADED, never silently nominal
        repl_fenced = self._shipper is not None and self._shipper.fenced
        # a failing ship/apply loop is a replica silently going stale (or a
        # primary silently not replicating): both loops deliberately record
        # the error and clear it on the next clean pass — surface it, or a
        # dead link is invisible until staleness bites the readers
        # the applier's error only counts while we ARE a follower: promotion
        # parks the applier with whatever its last poll recorded (a frame torn
        # by the dying primary, typically) frozen forever — folding that into
        # the promoted primary's health would alert on the healthy new writer
        # for the dead lineage's sins (the string stays visible in the
        # replication section for post-mortems)
        repl_link_error = (
            self._shipper is not None and self._shipper.last_error is not None
        ) or (
            self._repl_follower
            and self._applier is not None
            and self._applier.last_error is not None
        )
        if quarantined:
            state = "QUARANTINED"
        elif (
            degraded
            or zombies
            or shedding
            or wal_disabled
            or repl_fenced
            or repl_link_error
            or any(snap["state"] != "closed" for snap in breakers.values())
        ):
            state = "DEGRADED"
        else:
            state = "SERVING"
        out: Dict[str, Any] = {
            "state": state,
            "closed": closed,
            "worker_alive": worker is not None and worker.is_alive() and not degraded,
            "worker_restarts": restarts,
            "zombie_workers": zombies,
            "queue_depth": queue_depth,
            "shedding": shedding,
            "wal_disabled": wal_disabled,
            "breakers": breakers,
            "quarantined_tenants": dict(guard.quarantine.active()) if guard is not None else {},
        }
        if self._repl_cfg is not None:
            out["replication"] = self._replication_health()
        cluster = self._cluster
        if cluster is not None:
            out["cluster"] = cluster.health_view()
        if guard is not None:
            guard.publish_health(state)
        # health-transition observer (GuardConfig.on_health_transition): detect
        # under the lock (exactly once per transition, however many concurrent
        # health() readers observe it), fire OUTSIDE every lock, absorb errors
        hook_args: Optional[Tuple[str, str]] = None
        with self._lock:
            if state != self._last_health_state:
                hook_args = (self._last_health_state, state)
                self._last_health_state = state
        if hook_args is not None and _OBS.enabled:
            # flight-recorder evidence trail (+ bundle dump on QUARANTINED):
            # fires on the same once-per-edge detection the user hook rides
            _obs.record_health_transition(self.telemetry.engine_id, *hook_args)
        if hook_args is not None and guard is not None and guard.cfg.on_health_transition is not None:
            try:
                guard.cfg.on_health_transition(*hook_args)
            except Exception as exc:  # noqa: BLE001 — an observer crash must not poison health reads
                # ...but it must not vanish either: transitions fire ONCE per
                # edge, so a swallowed failover-hook raise (promote() refusing
                # an unbootstrapped follower, say) means automatic failover is
                # permanently lost for this quarantine — the operator needs a
                # signal to intervene
                warnings.warn(
                    f"on_health_transition({hook_args[0]!r} -> {hook_args[1]!r}) raised "
                    f"{type(exc).__name__}: {exc} — the transition will not re-fire; if this "
                    "was the replication failover hook, promote the follower manually",
                    RuntimeWarning,
                    stacklevel=2,
                )
        return out

    def _publish_health(self) -> None:
        """Refresh the obs health gauge after a state transition (no-op without guard)."""
        if self._guard is not None:
            self.health()

    def telemetry_snapshot(self) -> Dict[str, Any]:
        snap = self.telemetry.snapshot()
        snap["fused"] = self._fused
        snap["degraded"] = self._degraded
        snap["quarantined"] = self._quarantined
        snap["tenants"] = len(self._keyed.keys)
        tier = self._tier
        if tier is not None:
            snap["tenants"] += len(tier.warm) + len(tier.cold)
            snap["tier"] = {
                "hot": len(self._keyed.keys),
                "warm": len(tier.warm),
                "cold": len(tier.cold),
                "pinned": len(tier.pinned),
            }
        if isinstance(self._keyed, KeyedState):
            snap["slab_bytes"] = sum(self._slab_bytes().values())
        if self._ckpt_writer is not None:
            snap["ckpt_generation"] = self._ckpt_writer.last_generation
            snap["wal_seq"] = self._wal_seq
        return snap

    # ------------------------------------------------------------------ internals

    def _alloc_slot(self, key: Hashable) -> Optional[int]:
        tier = self._tier
        if tier is not None:
            if not self._is_resident(key) and tier.has(key):
                # non-resident tenant: leave the slot unresolved — the
                # dispatcher readmits it (one device_put-backed slot install)
                # right before the micro-batch that needs the row, under the
                # dispatch lock. Doing it here would put disk IO and slab
                # scatters on the caller's submit path.
                return None
        return self._keyed.slot_for(key)

    def _is_resident(self, key: Hashable) -> bool:
        """O(1) hot-tier membership (``keyed.keys`` materialises a tuple)."""
        keyed = self._keyed
        table = keyed._slots if isinstance(keyed, KeyedState) else keyed._states
        return key in table

    def _sync_state(self, state: Any) -> Any:
        # one collective sync at a time per process (_sync_state_lock): every
        # rank must issue collectives in the same order, and the breaker's
        # last_report() judging below must not see another call's report
        with self._sync_state_lock:
            return self._sync_state_inner(state)

    def _sync_state_inner(self, state: Any) -> Any:
        # multi-host serving rides the comm plane (codecs, coalesced transfers,
        # retry/degradation ladder) with its own site label so engine syncs are
        # attributable separately from bare sync_state_host callers
        guard = self._guard
        breaker = guard.comm_breaker if guard is not None else None
        if breaker is not None and not breaker.permit():
            # repeated degraded/stale syncs: pin sync=False for the probation —
            # local state NOW beats a retry ladder walk that ends stale anyway
            self.telemetry.count("sync_pinned")
            return state
        from metrics_tpu.comm import plane as _comm_plane

        # only reports THIS call produced may judge the breaker: the
        # single-process identity path publishes nothing, and a stale report
        # from an earlier sync must not re-trip a healthy probe. For a
        # collection, EVERY member's sync is judged — one member walking the
        # ladder to stale local state makes the whole result partially stale.
        prev = _comm_plane.last_report() if breaker is not None else None
        degraded = False
        conclusive = False

        def _judge() -> None:
            nonlocal prev, degraded, conclusive
            report = _comm_plane.last_report()
            if report is not None and report is not prev and report.site == "engine.compute":
                conclusive = True
                # live_subset is a SUCCESSFUL sync over the agreed surviving
                # ranks — exact for cumulative state, not stale. Tripping the
                # breaker on it would pin sync=False and turn one dead peer
                # into N disjoint local aggregates, which is strictly worse.
                if report.stale or report.degraded_step not in ("none", "live_subset"):
                    degraded = True
            prev = report

        try:
            if isinstance(self._metric, MetricCollection):
                synced = {}
                for name, sub in state.items():
                    synced[name] = sync_state_host(
                        sub, self._metric._modules[name]._reductions, site="engine.compute"
                    )
                    if breaker is not None:
                        _judge()
            else:
                synced = sync_state_host(state, self._metric._reductions, site="engine.compute")
                if breaker is not None:
                    _judge()
        except Exception:
            if breaker is not None:
                breaker.record_failure()
            raise
        if breaker is not None:
            if degraded:
                breaker.record_failure()
            elif conclusive:
                breaker.record_success()
            else:
                breaker.abandon_probe()
        return synced

    # -------------------------------------------------------------- tier plane

    def _ensure_tier(self) -> TierManager:
        """The residency manager — materialised lazily when replayed residency
        records or a tiered snapshot arrive on an engine built without
        ``tier=``. A lazy manager is mechanics only (demoted state stays
        readmittable); the eviction pass never runs without an operator-
        configured policy."""
        if self._tier is None:
            self._tier = TierManager(TierConfig(), self._metric)
            self._tier_policy = False
        return self._tier

    def _resolve_slot(self, key: Hashable) -> Optional[int]:
        """Slot for ``key``, readmitting it first if it lives in a lower tier
        (caller holds the dispatch lock). The tier check comes BEFORE the slot
        table: a submit can race a demotion and allocate a fresh slot for a
        key whose state sits in the warm mirror — promotion restores that
        state over the freshly-init row before any update touches it."""
        tier = self._tier
        if tier is not None and tier.has(key):
            return self._promote_tenant(key)
        keyed = self._keyed
        if isinstance(keyed, KeyedState):
            slot = keyed._slots.get(key)
            return slot if slot is not None else keyed.slot_for(key)
        return keyed.slot_for(key)

    def _promote_tenant(self, key: Hashable) -> Optional[int]:
        """Readmit one non-resident tenant into the slab (dispatch lock held).

        Warm path: one host→device scatter per captured row. Cold path: the
        spill blob restores through the MTCKPT1 container (bit-identical), and
        the file is deleted only AFTER the promote record — which embeds the
        entry — is journaled, so recovery never dereferences a dead pointer.
        """
        tier = self._tier
        src = tier.tier_of(key)
        with _obs.engine_span("engine.tier_promote", source=src or HOT):
            entry, _ = tier.pop_entry(key)
            keyed = self._keyed
            slot = keyed.slot_for(key)
            keyed.ensure_capacity()
            spill = entry.pop("_spill_file", None) if entry is not None else None
            if self._journal is not None:
                blob = b"" if entry is None else ckpt_format.dumps(
                    entry, meta={"kind": "tier-promote"}
                )
                self._journal_append(
                    [_encode_tier_record(b"P", int(slot or 0), self._key_bytes(key), blob)]
                )
                if slot is not None:
                    self._wal_slots_sent.add(slot)
            if entry is not None:
                restore_entry(keyed, key, entry)
            if spill is not None and tier.store is not None:
                tier.store.delete(spill)
        self.telemetry.count("tier_promotions")
        _obs.record_tier_promotion(self.telemetry.engine_id, src or "unknown")
        return slot

    def _demote_tenant(self, key: Hashable) -> bool:
        """Demote one hot tenant to the warm mirror (dispatch lock held).

        Capture → journal → evict → release: the demote record lands before
        the slot becomes reusable, so replay reproduces retire-then-reuse in
        commit order and a recovered engine never aliases the freed row."""
        keyed = self._keyed
        if not self._is_resident(key):
            return False
        with _obs.engine_span("engine.tier_demote"):
            entry = capture_entry(keyed, key)
            if self._journal is not None:
                slot = keyed._slots.get(key, 0) if isinstance(keyed, KeyedState) else 0
                self._journal_append(
                    [_encode_tier_record(b"D", int(slot), self._key_bytes(key))]
                )
            slot = keyed.evict(key)
            keyed.release_slot(slot)
            if slot is not None:
                self._wal_slots_sent.discard(slot)
            tier = self._tier
            tier.warm[key] = entry
            tier.forget_heat(key)
        self.telemetry.count("tier_demotions")
        _obs.record_tier_demotion(self.telemetry.engine_id)
        return True

    def _maybe_tier(self) -> None:
        """The between-batches eviction pass (dispatcher thread, like
        ``_maybe_checkpoint``): demote the coldest hot tenants down to
        ``hot_capacity`` (quarantined first, pinned never), then push warm
        overflow to disk. Spill IO runs OFF the dispatch lock — only the
        manifest flip retakes it — so readmissions never queue behind a disk
        write."""
        tier = self._tier
        if tier is None or not self._tier_policy:
            return
        keyed = self._keyed
        hot_count = len(keyed._slots) if isinstance(keyed, KeyedState) else len(keyed._states)
        if not tier.due(hot_count):
            return
        guard = self._guard
        quarantined = (
            set(guard.quarantine.active()) if guard is not None else set()
        )
        with self._dispatch_lock:
            hot_keys = keyed.keys
            for key in tier.victims(hot_keys, len(hot_keys) - tier.cfg.hot_capacity, quarantined):
                self._demote_tenant(key)
        store = tier.store
        if store is not None:
            for key in tier.spill_victims():
                with self._dispatch_lock:
                    entry = tier.warm.get(key)
                if entry is None:
                    continue  # promoted between passes
                try:
                    name, blob = store.spill(key, entry)
                except Exception:  # noqa: BLE001 — disk trouble: stay warm, stay serving
                    self.telemetry.count("tier_spill_failures")
                    break
                with self._dispatch_lock:
                    flipped = tier.warm.get(key) is entry
                    if flipped:
                        del tier.warm[key]
                        tier.cold[key] = name
                if not flipped:
                    store.delete(name)  # promoted while we wrote: orphaned file
                    continue
                self.telemetry.count("tier_spills")
                _obs.record_tier_spill(self.telemetry.engine_id, len(blob))
        self._publish_tier_gauges()

    def _slab_bytes(self) -> Dict[str, int]:
        """Device bytes held by the stacked slab (live + ring), per dtype."""
        keyed = self._keyed
        out: Dict[str, int] = {}
        if not isinstance(keyed, KeyedState):
            return out
        trees = [keyed.stacked] + [snap for _, snap in (keyed._ring or [])]
        for tree in trees:
            for leaf in jax.tree_util.tree_flatten(tree)[0]:
                dtype = np.dtype(leaf.dtype)
                out[dtype.name] = out.get(dtype.name, 0) + int(leaf.size) * dtype.itemsize
        return out

    def _publish_tier_gauges(self) -> None:
        if not _OBS.enabled:
            return
        eid = self.telemetry.engine_id
        tier = self._tier
        if tier is not None:
            hot = len(self._keyed._slots) if isinstance(self._keyed, KeyedState) else len(self._keyed._states)
            _obs.set_tier_residency(eid, hot, len(tier.warm), len(tier.cold))
        for dtype, nbytes in self._slab_bytes().items():
            _obs.set_engine_slab_bytes(eid, dtype, nbytes, shard=self._shard_label)

    def register_tenants(self, keys: Sequence[Hashable]) -> int:
        """Register tenants as COLD residents — one manifest entry each, no
        slab growth, no spill file. This is the million-tenant entry point: a
        registered-but-silent tenant costs nothing on the device until its
        first submit readmits it. Returns how many keys were newly registered
        (already-known keys, hot or tiered, are left untouched)."""
        tier = self._tier
        if tier is None:
            raise MetricsTPUUserError(
                "register_tenants() requires the engine to be built with tier=TierConfig(...)"
            )
        self._check_writable("register_tenants")
        keyed = self._keyed
        table = keyed._slots if isinstance(keyed, KeyedState) else keyed._states
        added = 0
        with self._dispatch_lock:
            for key in keys:
                if key in table:
                    continue
                if tier.register_cold(key):
                    added += 1
        return added

    def pin_tenant(self, key: Hashable) -> None:
        """Exempt ``key`` from tier eviction; a non-resident pinned tenant is
        readmitted immediately (pinning promises slab residency)."""
        tier = self._tier
        if tier is None:
            raise MetricsTPUUserError(
                "pin_tenant() requires the engine to be built with tier=TierConfig(...)"
            )
        self._check_writable("pin_tenant")
        with self._dispatch_lock:
            tier.pinned.add(key)
            if not self._is_resident(key) and tier.has(key):
                self._promote_tenant(key)

    def unpin_tenant(self, key: Hashable) -> None:
        if self._tier is not None:
            with self._dispatch_lock:
                self._tier.pinned.discard(key)

    def demote_tenant(self, key: Hashable) -> bool:
        """Demote one tenant to the warm mirror now (ops hook; flushes first).
        Returns False if the key is unknown or already non-resident."""
        tier = self._tier
        if tier is None:
            raise MetricsTPUUserError(
                "demote_tenant() requires the engine to be built with tier=TierConfig(...)"
            )
        self._check_quarantined("demote_tenant")
        self._check_writable("demote_tenant")
        self.flush()
        with self._dispatch_lock:
            if key in tier.pinned:
                return False
            return self._demote_tenant(key)

    def evict_tenant(self, key: Hashable) -> bool:
        """Forget ``key`` entirely — state, window history, residency records.

        The retirement is journaled (``b"T"``) BEFORE the slot id returns to
        the free-list, so WAL replay reproduces retire-then-reuse in commit
        order and a recovered engine never aliases the freed accumulator row
        onto whichever new tenant reused it. Works on untiered engines too
        (the slot still recycles instead of burning watermark)."""
        self._check_quarantined("evict_tenant")
        self._check_writable("evict_tenant")
        # per-tenant barrier, not flush(): only THIS key's accepted rows must
        # commit before the retirement record — waiting for the whole engine
        # to go idle would wedge eviction under sustained neighbour traffic
        self.drain_tenant(key)
        with self._dispatch_lock:
            keyed = self._keyed
            resident = self._is_resident(key)
            tiered = self._tier is not None and self._tier.has(key)
            if not resident and not tiered:
                return False
            if self._journal is not None:
                slot = keyed._slots.get(key, 0) if isinstance(keyed, KeyedState) else 0
                self._journal_append(
                    [_encode_tier_record(b"T", int(slot), self._key_bytes(key))]
                )
            if self._tier is not None:
                self._tier.discard(key)
                self._tier.forget_heat(key)
                self._tier.pinned.discard(key)
            if resident:
                slot = keyed.evict(key)
                keyed.release_slot(slot)
                if slot is not None:
                    self._wal_slots_sent.discard(slot)
        self.telemetry.count("tier_evictions")
        return True

    def tenant_tier(self, key: Hashable) -> Optional[str]:
        """Which tier ``key`` currently occupies: "hot" / "warm" / "cold",
        or ``None`` for an unknown tenant."""
        with self._dispatch_lock:
            if self._is_resident(key):
                return HOT
            return self._tier.tier_of(key) if self._tier is not None else None

    def tier_stats(self) -> Dict[str, Any]:
        """Residency counts + device slab footprint, one plain dict."""
        with self._dispatch_lock:
            keyed = self._keyed
            hot = len(keyed._slots) if isinstance(keyed, KeyedState) else len(keyed._states)
            out: Dict[str, Any] = {
                "hot": hot,
                "warm": 0,
                "cold": 0,
                "pinned": 0,
                "slab_bytes": sum(self._slab_bytes().values()),
            }
            tier = self._tier
            if tier is not None:
                out["warm"] = len(tier.warm)
                out["cold"] = len(tier.cold)
                out["pinned"] = len(tier.pinned)
                if self._tier_policy:
                    out["hot_capacity"] = tier.cfg.hot_capacity
        return out

    def export_tenant(
        self, key: Hashable, *, retire: bool = True
    ) -> Optional[Dict[str, Any]]:
        """Capture one tenant's full entry, whatever tier it occupies — the
        shard plane's migration source. Returns ``None`` for an unknown key.

        With ``retire=True`` the tenant is also forgotten here, journaled like
        :meth:`evict_tenant` so a recovered engine agrees it left. With
        ``retire=False`` the capture is a pure read (no journal record, no
        eviction) — the caller retires the source copy itself once the
        destination copy is durable (that's the shard resize write-ahead
        ordering)."""
        self._check_quarantined("export_tenant")
        with self._dispatch_lock:
            keyed = self._keyed
            entry: Optional[Dict[str, Any]] = None
            if self._is_resident(key):
                entry = capture_entry(keyed, key)
            elif self._tier is not None and self._tier.has(key):
                peeked = self._tier.peek_entry(key)
                entry = dict(peeked) if peeked is not None else {
                    "state": None, "ring": [], "rot": int(keyed.rotations)
                }
            else:
                return None
            entry.pop("_spill_file", None)
            if not retire:
                return entry
            if self._journal is not None:
                slot = keyed._slots.get(key, 0) if isinstance(keyed, KeyedState) else 0
                self._journal_append(
                    [_encode_tier_record(b"T", int(slot), self._key_bytes(key))]
                )
            if self._tier is not None:
                self._tier.discard(key)
                self._tier.forget_heat(key)
                self._tier.pinned.discard(key)
            if self._is_resident(key):
                slot = keyed.evict(key)
                keyed.release_slot(slot)
                if slot is not None:
                    self._wal_slots_sent.discard(slot)
        self.telemetry.count("tier_evictions")
        return entry

    def import_tenant(self, key: Hashable, entry: Optional[Dict[str, Any]]) -> None:
        """Install an exported tenant entry — the migration sink.

        Exports are captured live, so their ring rows occupy the last
        ``len(ring)`` source segments; re-stamping the entry with THIS
        engine's rotation counter places them in the same positions relative
        to the destination window (the two engines' absolute counters need
        not agree — a shard born mid-resize starts from zero, so its empty
        ring is padded with init segments first to give the rows somewhere to
        land). An entry with no state at all (a registered-but-silent cold
        tenant) stays off the slab when this engine is tiered — it lands as a
        cold registration, not a hot row."""
        self._check_quarantined("import_tenant")
        self._check_writable("import_tenant")
        with self._dispatch_lock:
            keyed = self._keyed
            rows: List[Any] = []
            if entry is not None:
                entry = dict(entry)
                entry.pop("_spill_file", None)
                entry["rot"] = int(keyed.rotations)
                rows = list(entry.get("ring") or [])
            empty = entry is None or (
                entry.get("state") is None and not any(r is not None for r in rows)
            )
            if empty and self._tier is not None and not self._is_resident(key):
                self._tier.discard(key)
                self._tier.register_cold(key)
                return
            ring = keyed._ring
            if rows and ring is not None and len(ring) < len(rows):
                if isinstance(keyed, KeyedState):
                    while len(ring) < len(rows):
                        ring.append((keyed.capacity, keyed._tiled(keyed.capacity)))
                else:
                    while len(ring) < len(rows):
                        ring.append({})
            slot = keyed.slot_for(key)
            keyed.ensure_capacity()
            if self._journal is not None:
                blob = b"" if entry is None else ckpt_format.dumps(
                    entry, meta={"kind": "tier-promote"}
                )
                self._journal_append(
                    [_encode_tier_record(b"P", int(slot or 0), self._key_bytes(key), blob)]
                )
                if slot is not None:
                    self._wal_slots_sent.add(slot)
            if self._tier is not None:
                self._tier.discard(key)
            if entry is not None:
                restore_entry(keyed, key, entry)

    # ---------------------------------------------------- durable state plane

    def _init_checkpoint(self, cfg: CheckpointConfig) -> None:
        if cfg.wal_flush not in _WAL_FLUSH:
            raise MetricsTPUUserError(f"`wal_flush` must be one of {_WAL_FLUSH}, got {cfg.wal_flush!r}")
        if cfg.wal_fsync not in _WAL_FSYNC:
            raise MetricsTPUUserError(f"`wal_fsync` must be one of {_WAL_FSYNC}, got {cfg.wal_fsync!r}")
        if cfg.wal_fsync == "interval" and cfg.wal_fsync_interval_s <= 0:
            raise MetricsTPUUserError(
                f"`wal_fsync_interval_s` must be > 0 in interval mode, got {cfg.wal_fsync_interval_s!r}"
            )
        self._wal_last_fsync = time.monotonic()
        self._ckpt_cfg = cfg
        self._ckpt_store = SnapshotStore(
            cfg.directory, retain=cfg.retain, rank=cfg.rank, world=cfg.world, durable=cfg.durable
        )
        if cfg.wal:
            self._journal = RequestJournal(cfg.directory, rank=cfg.rank, durable=cfg.durable)
        self._ckpt_writer = AsyncCheckpointer(
            self._ckpt_store,
            interval_s=cfg.interval_s,
            site="engine",
            policy=cfg.policy,
            schema_version=_ENGINE_SCHEMA_VERSION,
            on_commit=self._on_snapshot_commit,
            on_error=self._on_snapshot_error,
        )
        if cfg.resume:
            self._recover()

    def _on_snapshot_commit(self, generation: int, tree: Any, meta: Optional[Dict[str, Any]]) -> None:
        """Writer-thread callback: rotate the WAL past what every RETAINED
        generation covers. Rotating to the newest snapshot's seq would be
        wrong: if that file is later corrupted, recovery falls back to an
        older generation whose tail records must still be replayable — so the
        rotation point is the OLDEST retained generation's coverage."""
        self.telemetry.count("checkpoints")
        if self._guard is not None and self._guard.ckpt_breaker is not None:
            self._guard.ckpt_breaker.record_success()
        if self._journal is None:
            return
        self._snapshot_seqs[generation] = int(tree["seq"])
        retained = self._ckpt_store.generations()
        self._snapshot_seqs = {g: s for g, s in self._snapshot_seqs.items() if g in retained}
        covered = None
        for gen in retained:
            seq = self._snapshot_seqs.get(gen)
            if seq is None:
                try:  # generation committed by a previous process: read its meta
                    seq = int(self._ckpt_store.read_meta(gen).get("seq", -1))
                    self._snapshot_seqs[gen] = seq
                except Exception:  # noqa: BLE001 — unreadable: don't rotate past it
                    seq = -1
            covered = seq if covered is None else min(covered, seq)
        if covered is not None and covered >= 0:
            self._journal.rotate(covered_seq=covered)

    def _key_bytes(self, key: Hashable) -> bytes:
        key_bytes = self._wal_key_cache.get(key)
        if key_bytes is None:
            key_bytes = self._wal_key_cache[key] = pickle.dumps(
                key, protocol=pickle.HIGHEST_PROTOCOL
            )
        return key_bytes

    def _journal_append(self, payloads: List[bytes]) -> Optional[List[int]]:
        """Append + flush per policy; a journal IO failure disables the WAL
        (counted, remembered) instead of failing serving — durability degrades,
        availability does not."""
        try:
            seqs = self._journal.append_many(payloads)
            flush = self._ckpt_cfg.wal_flush
            fsync = flush == "fsync" or self._wal_fsync_due()
            if flush != "none" or fsync:
                self._journal.flush(fsync=fsync)
                if fsync:
                    self._wal_last_fsync = time.monotonic()
        except Exception as exc:  # noqa: BLE001
            self._wal_error = exc
            journal, self._journal = self._journal, None
            try:
                journal.close()  # release the fd; flush whatever still can be
            except Exception:  # noqa: BLE001 — already in the failure path
                pass
            self.telemetry.count("checkpoint_failures")
            if self._shipper is not None:
                # shipping from a dead journal would heartbeat a frozen seq —
                # the follower would report fresh while diverging unbounded
                self._shipper.mark_journal_lost()
            return None
        self._wal_seq = max(self._wal_seq, seqs[-1])
        self.telemetry.count("wal_records", len(payloads))
        return seqs

    def _wal_fsync_due(self) -> bool:
        """Does the ``wal_fsync`` policy demand a sync on this append?"""
        policy = self._ckpt_cfg.wal_fsync
        if policy == "commit":
            return True
        if policy == "interval":
            return time.monotonic() - self._wal_last_fsync >= self._ckpt_cfg.wal_fsync_interval_s
        return False

    def _journal_chunk(
        self,
        units: List[Tuple[_Request, Tuple[Any, ...], int, bool]],
        key_ids: Any,
        mask: Any,
        columns: Sequence[Any],
        ctxs: Sequence[_TraceContext] = (),
    ) -> None:
        """Journal one committed fused micro-batch as a single chunk record.

        Called AFTER the kernel committed and BEFORE the chunk's futures
        resolve: an acknowledged request is always either in a snapshot or
        replayable, and a chunk that failed to trace is never journaled (its
        eager retry journals per-request instead — no double entry). Replay
        reapplies the masked rows in scan order, so a snapshot at seq S plus
        records > S reproduces the lost process's state exactly once, bit-for-
        bit.
        """
        if self._journal is None:
            return
        new_slots = []
        for req, _, _, _ in units:
            if req.slot not in self._wal_slots_sent:
                self._wal_slots_sent.add(req.slot)
                new_slots.append((req.slot, self._key_bytes(req.key)))
        record = _encode_chunk_record(
            new_slots, np.asarray(key_ids), np.asarray(mask),
            [np.asarray(c) for c in columns], ctxs,
        )
        self._journal_append([record])

    def _journal_requests(self, reqs: List[_Request], args_override: Optional[Tuple[Any, ...]] = None) -> None:
        """Per-request WAL records for the non-fused paths (eager metrics,
        degraded/inline submits, eager retry). ``args_override`` journals a
        trimmed argument view when part of the request already committed (and
        was journaled) through fused chunks."""
        if self._journal is None:
            return
        todo = [req for req in reqs if req.seq is None]
        if not todo:
            return
        payloads = [
            _encode_request_record(
                self._key_bytes(req.key),
                req.args if args_override is None else args_override,
                req.ctx,
            )
            for req in todo
        ]
        seqs = self._journal_append(payloads)
        if seqs is not None:
            for req, seq in zip(todo, seqs):
                req.seq = seq

    def _checkpoint_view(self) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Consistent host-side snapshot tree of ALL tenant state + WAL position.

        Runs on the dispatcher thread between micro-batches (or on a quiesced
        caller thread) under the dispatch lock: jax arrays are immutable, so
        the device_get is the only copy and the submit path never stalls on it.
        """
        with self._dispatch_lock:
            keyed = self._keyed
            tree: Dict[str, Any] = {"kind": "engine", "seq": int(self._wal_seq)}
            # additive (schema v1 readers tolerate absence): the rotation
            # counter anchors demoted entries' absolute ring indices, and the
            # tier section makes the snapshot cover a PARTIALLY-RESIDENT
            # engine — warm entries by value, cold tenants by manifest pointer
            # (their spill files are already durable containers on disk)
            tree["rotations"] = int(keyed.rotations)
            if self._tier is not None:
                tree["tier"] = self._tier.snapshot_view()
            if isinstance(keyed, KeyedState):
                tree["mode"] = "fused"
                tree["capacity"] = int(keyed.capacity)
                tree["slots"] = dict(keyed._slots)  # non-str keys -> object leaf
                tree["stacked"] = jax.device_get(keyed.stacked)
                tree["ring"] = [
                    {"capacity": int(cap), "stacked": jax.device_get(snap)}
                    for cap, snap in (keyed._ring or [])
                ]
            else:
                keys = list(keyed._states)
                tree["mode"] = "eager"
                tree["keys"] = {"values": keys}  # wrapped: keys may be non-JSON-able
                tree["states"] = [jax.device_get(keyed._states[k]) for k in keys]
                tree["ring"] = [
                    {
                        "keys": {"values": list(seg)},
                        "states": [jax.device_get(seg[k]) for k in seg],
                    }
                    for seg in (keyed._ring or [])
                ]
        tenants = len(keyed.keys)
        if self._tier is not None:
            tenants += len(self._tier.warm) + len(self._tier.cold)
        meta = {"tenants": tenants, "seq": tree["seq"]}
        if self._repl_cfg is not None:
            # the lineage's fencing token: a recovered promoted node knows which
            # epoch it owns without re-walking the promotion
            meta["epoch"] = self._repl_epoch
        return tree, meta

    def _on_snapshot_error(self, exc: BaseException) -> None:
        """Writer-thread callback: count the absorbed failure, feed the breaker."""
        self.telemetry.count("checkpoint_failures")
        if self._guard is not None and self._guard.ckpt_breaker is not None:
            self._guard.ckpt_breaker.record_failure()

    def _maybe_checkpoint(self) -> None:
        if self._ckpt_writer is None:
            return
        breaker = self._guard.ckpt_breaker if self._guard is not None else None
        if breaker is not None:
            if not breaker.permit():
                # repeated commit failures: suspend snapshot attempts for the
                # (exponentially growing) probation instead of paying a doomed
                # serialize+write every interval — the WAL still covers the gap
                self.telemetry.count("ckpt_suspended")
                return
            issued = False
            try:
                issued = self._ckpt_writer.maybe_checkpoint(self._checkpoint_view)
            except Exception:  # noqa: BLE001 — a snapshot failure must not kill the dispatcher
                self.telemetry.count("checkpoint_failures")
                breaker.record_failure()
                return
            finally:
                if not issued and breaker is not None:
                    # nothing was attempted (not due / writer busy): a permitted
                    # half-open probe must not stay claimed forever
                    breaker.abandon_probe()
            return
        try:
            self._ckpt_writer.maybe_checkpoint(self._checkpoint_view)
        except Exception:  # noqa: BLE001 — a snapshot failure must not kill the dispatcher
            self.telemetry.count("checkpoint_failures")

    def checkpoint_now(self) -> Optional[int]:
        """Flush, then snapshot synchronously; returns the committed generation.

        ``None`` when checkpointing is off or the write failed (the failure is
        counted and kept on ``self._ckpt_writer.last_error``, never raised).
        """
        if self._ckpt_writer is None or self._quarantined:
            return None
        self.flush()
        return self._ckpt_writer.checkpoint_sync(self._checkpoint_view)

    def _validate_engine_snapshot(self, snap: Any) -> None:
        tree = snap.tree
        if snap.schema_version != _ENGINE_SCHEMA_VERSION:
            raise ValueError(f"engine snapshot schema v{snap.schema_version} != v{_ENGINE_SCHEMA_VERSION}")
        if not isinstance(tree, dict) or tree.get("kind") != "engine":
            raise ValueError("not an engine snapshot")
        tier_view = tree.get("tier")
        if tier_view is not None and not isinstance(tier_view, dict):
            raise ValueError("engine snapshot tier section is not a mapping")
        mode = tree.get("mode")
        ref_leaves, ref_def = jax.tree_util.tree_flatten(self._metric.init_state())
        if mode == "fused":
            if not isinstance(self._keyed, KeyedState):
                raise ValueError("fused snapshot but the live engine serves eagerly")
            cap = int(tree["capacity"])
            for entry in [tree] + list(tree.get("ring", [])):
                leaves, treedef = jax.tree_util.tree_flatten(entry["stacked"])
                if treedef != ref_def:
                    raise ValueError("stacked state structure does not match the live metric")
                ecap = int(entry["capacity"]) if "capacity" in entry else cap
                for ref, got in zip(ref_leaves, leaves):
                    if np.dtype(got.dtype) != np.dtype(ref.dtype) or tuple(got.shape) != (ecap, *ref.shape):
                        raise ValueError(
                            f"stacked leaf {np.dtype(got.dtype).name}{tuple(got.shape)} does not match "
                            f"live {np.dtype(ref.dtype).name}{(ecap, *ref.shape)}"
                        )
        elif mode == "eager":
            ref = self._metric.init_state()
            for entry in [tree] + list(tree.get("ring", [])):
                if len(entry["keys"]["values"]) != len(entry["states"]):
                    raise ValueError("eager snapshot keys/states length mismatch")
                for st in entry["states"]:
                    # top-level key check only: ragged cat lists make full
                    # treedef comparison reject legitimate snapshots
                    if not isinstance(st, dict) or set(st) != set(ref):
                        raise ValueError("eager state structure does not match the live metric")
        else:
            raise ValueError(f"unknown engine snapshot mode {mode!r}")

    def _restore_keyed(self, tree: Dict[str, Any]) -> None:
        if tree["mode"] == "fused":
            if not isinstance(self._keyed, KeyedState):
                raise ValueError("fused snapshot but the live engine serves eagerly")
            keyed = KeyedState(
                self._metric, capacity=tree["capacity"], window=self._window,
                device=self._device,
            )
            keyed.capacity = int(tree["capacity"])
            keyed.stacked = jax.tree.map(jnp.asarray, tree["stacked"])
            keyed._slots = dict(tree["slots"])
            # the allocation watermark must survive restore: a recovered
            # primary / promoted follower taking a NEW tenant after this would
            # otherwise be handed slot 0 — an existing tenant's accumulator row
            keyed._max_slot = max(keyed._slots.values(), default=-1)
            if keyed._ring is not None:
                for entry in tree.get("ring", []):
                    keyed._ring.append(
                        (int(entry["capacity"]), jax.tree.map(jnp.asarray, entry["stacked"]))
                    )
            keyed.rotations = int(tree.get("rotations", 0))
            self._keyed = keyed
        else:
            # an eager snapshot (e.g. the crashed engine had demoted) restores
            # into a fused-capable engine by demoting it up front — recovering
            # slower always beats refusing to recover
            if not isinstance(self._keyed, EagerKeyedState):
                self._fused = False
                self._kernels.clear()
            keyed = EagerKeyedState(self._metric, window=self._window)
            keyed._states = dict(zip(tree["keys"]["values"], tree["states"]))
            if keyed._ring is not None:
                for entry in tree.get("ring", []):
                    keyed._ring.append(dict(zip(entry["keys"]["values"], entry["states"])))
            keyed.rotations = int(tree.get("rotations", 0))
            self._keyed = keyed
        # residency map: a snapshot with a tier section restores into a
        # partially-resident engine (follower bootstrap inherits it the same
        # way); one without clears any stale local map — old fully-hot
        # snapshots restore exactly as before the tier plane existed
        view = tree.get("tier")
        if view:
            self._ensure_tier().restore_view(view)
        elif self._tier is not None:
            self._tier.restore_view({})

    @staticmethod
    def _chunk_signature(columns: Sequence[np.ndarray]) -> Signature:
        """Rebuild the request signature a chunk record's columns were padded
        under: column shape is (bucket, 1, *trailing), so the signature's
        trailing shape is ``col.shape[2:]`` (dtypes were canonicalized before
        padding, but re-canonicalize for robustness across x64 settings)."""
        return tuple(
            (tuple(int(s) for s in col.shape[2:]),
             np.dtype(jax.dtypes.canonicalize_dtype(col.dtype)).name)
            for col in columns
        )

    def _replay_chunk(self, payload: bytes) -> None:
        """Re-apply one fused micro-batch record.

        A fused engine replays it through its OWN bucket kernel — the record
        holds the padded columns + key ids + mask exactly as the primary's
        kernel saw them, so one compiled dispatch reproduces the committed
        result bit-for-bit at full speed (what lets a follower keep pace with
        a fused primary). Slot intros install the PRIMARY'S ids (key_ids index
        by them; intros may arrive gapped because chunk commit order is not
        slot assignment order). A demoted/eager engine — or a chunk whose
        update cannot trace here — falls back to the per-row host walk, which
        is the same scan semantics, only slower.
        """
        off = 1
        (n_new,) = struct.unpack_from("<H", payload, off)
        off += 2
        for _ in range(n_new):
            (slot,) = _WAL_U32.unpack_from(payload, off)
            off += 4
            (klen,) = _WAL_U32.unpack_from(payload, off)
            off += 4
            key = pickle.loads(payload[off : off + klen])
            off += klen
            self._replay_slot_keys[slot] = key
            if isinstance(self._keyed, KeyedState):
                self._keyed.install_slot(key, slot)
        ncols = payload[off]
        off += 1
        key_ids, off = _dec_array(payload, off)
        mask, off = _dec_array(payload, off)
        columns = []
        for _ in range(ncols):
            col, off = _dec_array(payload, off)
            columns.append(col)
        keyed = self._keyed
        if isinstance(keyed, KeyedState):
            max_id = int(key_ids.max()) + 1 if len(key_ids) else 0
            if keyed.ensure_capacity(min_slots=max_id):
                self.telemetry.count("key_growths")
                self.telemetry.observe_resize(keyed.last_resize_s)
            try:
                kernel = self._get_kernel(
                    self._chunk_signature(columns), int(len(key_ids)), keyed.capacity
                )
                # no block_until_ready here (unlike live dispatch): replay has
                # no future to ack, and letting the applier pipeline chunk
                # kernels is what keeps a follower abreast of a fused primary;
                # readers force the value when they consume it
                keyed.stacked = kernel(
                    keyed.stacked,
                    jnp.asarray(key_ids),
                    jnp.asarray(mask),
                    *[jnp.asarray(c) for c in columns],
                )
                return
            except _FusedUnsupported:
                pass  # untraceable on this engine: per-row host walk below
            for i in range(len(key_ids)):
                if not mask[i]:
                    continue
                key = self._replay_slot_keys[int(key_ids[i])]
                rows = tuple(col[i] for col in columns)  # (1, *trailing) — the scan slice
                state = keyed.state_of(key)
                keyed.set_state(key, self._metric.update_state(state, *rows))
        else:
            for i in range(len(key_ids)):
                if not mask[i]:
                    continue
                key = self._replay_slot_keys[int(key_ids[i])]
                keyed.slot_for(key)
                rows = tuple(col[i] for col in columns)
                keyed.update(key, *rows)

    def _replay_request(
        self,
        key: Hashable,
        args: Tuple[Any, ...],
        ctx: Optional[_TraceContext] = None,
    ) -> None:
        """Re-apply one 'R' record as ONE whole-request update — exactly how
        the eager/inline paths that produce these records applied it (fused
        work replays through chunk records instead), so float accumulation
        rounds identically to the lost process."""
        if (
            self._tier is not None
            and not self._is_resident(key)
            and self._tier.has(key)
        ):
            # defensive: the primary journals a P record before any R for a
            # non-resident tenant, but an older snapshot's tier section can
            # still mark the key non-resident at this point in the replay
            entry, _ = self._tier.pop_entry(key)
            self._keyed.slot_for(key)
            self._keyed.ensure_capacity()
            if entry is not None:
                restore_entry(self._keyed, key, entry)
        if isinstance(self._keyed, EagerKeyedState):
            self._keyed.slot_for(key)
            self._keyed.update(key, *args)
        else:
            self._keyed.slot_for(key)
            self._keyed.ensure_capacity()
            state = self._keyed.state_of(key)
            self._keyed.set_state(key, self._metric.update_state(state, *args))

    # -------------------------------------------- tier residency-record replay

    def _replay_demote(self, payload: bytes) -> None:
        """Replay one b"D" record: capture the tenant's row from the REPLAYED
        slab (bit-identical to what the journaling engine captured, because
        replay is bit-identical up to this record), park it warm, free the
        slot. The live engine may later have spilled the entry to disk —
        content is what matters; tier placement is local policy."""
        _, key, _ = _decode_tier_record(payload)
        if not self._is_resident(key):
            return  # snapshot already reflects the demotion
        tier = self._ensure_tier()
        entry = capture_entry(self._keyed, key)
        slot = self._keyed.evict(key)
        self._keyed.release_slot(slot)
        if slot is not None:
            self._replay_slot_keys.pop(slot, None)
            self._wal_slots_sent.discard(slot)
        tier.warm[key] = entry
        tier.forget_heat(key)

    def _replay_retire(self, payload: bytes) -> None:
        """Replay one b"T" record: forget the tenant in every tier."""
        _, key, _ = _decode_tier_record(payload)
        if self._tier is not None:
            self._tier.discard(key)
            self._tier.forget_heat(key)
        if self._is_resident(key):
            slot = self._keyed.evict(key)
            self._keyed.release_slot(slot)
            if slot is not None:
                self._replay_slot_keys.pop(slot, None)
                self._wal_slots_sent.discard(slot)

    def _replay_promote(self, payload: bytes) -> None:
        """Replay one b"P" record: install the journaling engine's slot id and
        restore the embedded entry blob through the MTCKPT1 path — never the
        spill file, which the live engine deleted the moment this record was
        durable."""
        slot, key, blob = _decode_tier_record(payload)
        keyed = self._keyed
        if isinstance(keyed, KeyedState):
            keyed.install_slot(key, slot)
            self._replay_slot_keys[slot] = key
            keyed.ensure_capacity(min_slots=slot + 1)
        else:
            keyed.slot_for(key)
        if blob:
            restore_entry(keyed, key, ckpt_format.loads(blob).tree)
        if self._tier is not None:
            self._tier.discard(key)

    def _recover(self) -> None:
        """Restart path: newest valid snapshot + exactly-once WAL replay."""
        t0 = time.perf_counter()
        found = self._ckpt_store.latest_valid(validate=self._validate_engine_snapshot)
        if found is not None:
            gen, snap = found
            with self._dispatch_lock:
                self._restore_keyed(snap.tree)
            self._wal_seq = int(snap.tree.get("seq", -1))
            if snap.tree["mode"] == "fused":
                # chunk records reference slot ids; mappings introduced before
                # the snapshot live in rotated-away segments, so seed the
                # table from the snapshot's own slot map
                self._replay_slot_keys = {
                    slot: key for key, slot in snap.tree["slots"].items()
                }
            self.telemetry.count("recoveries")
            _obs.record_ckpt_io(
                "engine", "restore",
                os.path.getsize(self._ckpt_store.path(gen)),
                time.perf_counter() - t0, generation=gen,
            )
        if self._journal is not None:
            replayed = 0
            for seq, payload in self._journal.replay(after_seq=self._wal_seq):
                try:
                    with self._dispatch_lock:
                        self._apply_wal_payload(payload)
                except Exception:  # noqa: BLE001 — it failed when first accepted too
                    self.telemetry.count("failed")
                replayed += 1
                self._wal_seq = max(self._wal_seq, seq)
            if replayed:
                self.telemetry.count("replayed", replayed)

    # ---------------------------------------------------- replication plane

    def _init_replication(self, cfg: ReplConfig) -> None:
        self._repl_cfg = cfg
        self._repl_epoch = int(cfg.epoch)
        if cfg.role == "primary":
            if self._journal is None:
                raise MetricsTPUUserError(
                    "replication role 'primary' requires checkpoint=CheckpointConfig(..., wal=True): "
                    "the shipper publishes the durable plane's snapshot + WAL lineage"
                )
            # recover the lineage's fencing token: a restarted promoted node
            # must resume at the epoch it owns (recorded in snapshot meta at
            # promotion), or its own fence would reject its shipments
            resumed = bool(self._ckpt_store.generations())
            for gen in reversed(self._ckpt_store.generations()):
                try:
                    self._repl_epoch = max(
                        self._repl_epoch, int(self._ckpt_store.read_meta(gen).get("epoch", 0))
                    )
                    break
                except Exception:  # noqa: BLE001 — corrupt meta: fall back a generation
                    continue
            if resumed or self._wal_seq > -1:
                # every resume starts a NEW lineage epoch: a restarted primary
                # may RE-USE seqs its dead incarnation already shipped (a
                # non-fsynced WAL tail lost to power loss recovers behind
                # records the shipper read from the page cache and published),
                # and within one epoch the follower's seq chain would drop the
                # re-used seqs as duplicates — applying everything after them
                # onto divergent state, silently, while lag() reads caught-up.
                # The bump makes followers re-bootstrap from the restart
                # snapshot instead of trusting cross-incarnation arithmetic;
                # the pin snapshot persists it so a crash before the first
                # periodic snapshot cannot hand two incarnations one epoch.
                self._repl_epoch += 1
                if self._ckpt_writer is not None:
                    self._ckpt_writer.checkpoint_sync(self._checkpoint_view)
            self._shipper = Shipper(
                cfg,
                store=self._ckpt_store,
                journal=self._journal,
                telemetry=self.telemetry,
                engine_label=self.telemetry.engine_id,
                epoch=self._repl_epoch,
            )
        else:
            self._repl_follower = True
            self._applier = ReplicaApplier(
                self, cfg, telemetry=self.telemetry, engine_label=self.telemetry.engine_id
            )

    def _repl_reset_state(self) -> None:
        """Applier callback: drop ALL replica state (a wiped/replaced primary
        lineage restarted seq numbering — the old mirror is meaningless)."""
        with self._dispatch_lock:
            if isinstance(self._keyed, KeyedState):
                self._keyed = KeyedState(
                    self._metric, capacity=self._keyed.capacity, window=self._window,
                    device=self._device,
                )
            else:
                self._keyed = EagerKeyedState(self._metric, window=self._window)
            self._replay_slot_keys = {}
            if self._tier is not None:
                self._tier.restore_view({})

    def _repl_restore_snapshot(self, data: bytes) -> int:
        """Applier callback: bootstrap/rebootstrap from one shipped snapshot via
        the exact restore path recovery uses; returns the WAL seq it covers."""
        snap = ckpt_format.loads(data)
        self._validate_engine_snapshot(snap)
        with self._dispatch_lock:
            self._restore_keyed(snap.tree)
            if snap.tree["mode"] == "fused":
                # chunk records reference slot ids; mappings introduced before
                # the snapshot live in rotated-away segments (same seeding as
                # the local recovery path)
                self._replay_slot_keys = {slot: key for key, slot in snap.tree["slots"].items()}
        return int(snap.tree.get("seq", -1))

    def _apply_wal_payload(self, payload: bytes) -> None:
        """Dispatch one WAL record to its replayer (caller holds the dispatch lock).

        With obs on, each replayed record runs inside an ``engine.replay`` span
        carrying the trace ids the PRIMARY submit stamped into the record —
        the cross-host/cross-incarnation link: a follower's apply (via
        ``_repl_apply_record``) and a crash recovery's replay both land here,
        so their spans name the original trace_id."""
        if _OBS.enabled:
            attrs: Dict[str, Any] = {"kind": payload[:1].decode("latin1")}
            traces = _record_trace_hexes(payload)
            if traces:
                attrs["traces"] = traces
            with _obs.engine_span("engine.replay", **attrs):
                self._apply_wal_payload_inner(payload)
            return
        self._apply_wal_payload_inner(payload)

    def _apply_wal_payload_inner(self, payload: bytes) -> None:
        kind = payload[:1]
        if kind == b"C":
            self._replay_chunk(payload)
        elif kind == b"Z":
            self._keyed.reset()
            if self._tier is not None:
                for name in self._tier.reset():
                    if self._tier.store is not None:
                        self._tier.store.delete(name)
        elif kind == b"W":
            self._keyed.rotate()
        elif kind == b"D":
            self._replay_demote(payload)
        elif kind == b"T":
            self._replay_retire(payload)
        elif kind == b"P":
            self._replay_promote(payload)
        else:
            self._replay_request(*_decode_request_record(payload))

    def _repl_apply_record(self, payload: bytes) -> None:
        """Applier callback: replay ONE shipped WAL record — identical machinery
        to restart recovery, so the follower is bit-identical to the primary at
        every applied seq. A record that failed on the primary fails here too
        (counted, absorbed) — and still advances the seq chain, as it did there."""
        try:
            with self._dispatch_lock:
                self._apply_wal_payload(payload)
        except Exception:  # noqa: BLE001 — it failed when the primary first accepted it too
            self.telemetry.count("failed")

    def _repl_quiesce(self) -> None:
        """Applier callback: force the pending replay chain (called OUTSIDE the
        dispatch lock, once per received frame batch — bounds how much pending
        work a concurrent reader's value-force can inherit)."""
        keyed = self._keyed
        if isinstance(keyed, KeyedState):
            with self._dispatch_lock:
                stacked = keyed.stacked
            jax.block_until_ready(stacked)

    def replica_lag(self) -> Optional[ReplicaLag]:
        """This follower's staleness bound (``None`` unless role='follower').

        Every read path tags itself with this: ``compute``/``compute_all``
        refuse beyond the configured ``max_staleness``, ``health()`` embeds it
        under ``"replication"``, and the master-gated lag gauges mirror it.
        """
        applier = self._applier
        if applier is None or not self._repl_follower:
            return None
        lag = applier.lag()
        _obs.set_repl_lag(self.telemetry.engine_id, lag.seqs_behind, lag.seconds_behind)
        return lag

    def _check_staleness(self) -> None:
        """Refuse a follower read beyond the configured staleness bound."""
        applier = self._applier
        if applier is None or not self._repl_follower:
            return
        cfg = self._repl_cfg
        bounded = cfg.max_staleness_seqs is not None or cfg.max_staleness_s is not None
        if not bounded:
            return
        if not applier.bootstrapped:
            self.telemetry.count("stale_read_refusals")
            raise StalenessExceeded(
                "read refused: replica has not bootstrapped from the primary yet "
                "(its staleness is unbounded)"
            )
        lag = applier.lag()
        if lag.exceeds(cfg.max_staleness_seqs, cfg.max_staleness_s):
            self.telemetry.count("stale_read_refusals")
            raise StalenessExceeded(
                f"read refused: replica lag ({lag.seqs_behind} seqs, {lag.seconds_behind:.3f}s) "
                f"exceeds max_staleness (seqs={cfg.max_staleness_seqs}, s={cfg.max_staleness_s})"
            )

    def promote(self, *, epoch: Optional[int] = None, ship: Optional[ReplConfig] = None) -> None:
        """Follower → primary hot failover.

        Drains the shipped tail (everything the deposed primary published is
        applied — the promoted node serves exactly the acked prefix, no loss,
        no double-apply: the seq chain drops duplicates and parks on gaps),
        fences the transport at ``deposed epoch + 1`` (a zombie primary's late
        shipments are rejected at the transport boundary from that instant),
        re-opens this node's OWN durable lineage (``promote_checkpoint``) with
        a synchronous pin snapshot, and starts a dispatcher — the engine is
        writable when this returns. Idempotent; triggered explicitly or by the
        guard hook (``GuardConfig(on_health_transition=repl.failover_hook(...))``).

        ``epoch`` overrides the fencing epoch (must exceed the applied lineage
        epoch) — the cluster plane passes its lease epoch here so *holding the
        lease* and *writing into the lineage* are one fact. ``ship`` is a
        ``role="primary"`` ReplConfig installed after promotion: the new
        primary immediately re-ships its lineage (the cluster node hands it a
        fan-out transport over the surviving peers).
        """
        cfg = self._repl_cfg
        if cfg is None or cfg.role != "follower":
            raise MetricsTPUUserError("promote() requires replication=ReplConfig(role='follower')")
        if ship is not None and ship.role != "primary":
            raise MetricsTPUUserError(
                f"promote(ship=...) must be a role='primary' ReplConfig, got role={ship.role!r}"
            )
        with self._promote_lock:
            if not self._repl_follower:
                return  # already promoted (explicit call raced the failover hook)
            applier = self._applier
            if applier is None:
                raise NotPromotableError(
                    "promote(): this node is a demoted, unattached follower — it has no "
                    "ship link to drain a lineage from; re-attach it (demote(follower_cfg)) "
                    "and retry once it bootstraps"
                )
            if not applier.bootstrapped:
                # a replica that never received its bootstrap snapshot holds
                # FRESH INIT state: flipping it writable would pin empty state
                # as the authoritative new lineage — every tenant's history
                # silently replaced by zeros served as legitimate. Refuse;
                # retryable by contract (NotPromotableError): the guard hook
                # and the cluster orchestrator back off and retry once a
                # snapshot lands — conservative, loud, never lossy. An
                # EMPTY-bootstrap replica is promotable: its primary genuinely
                # had no state.
                raise NotPromotableError(
                    "promote(): this follower never bootstrapped — promoting would pin "
                    "fresh-init state as the new durable lineage, losing all tenant "
                    "history; retry once a snapshot has been applied"
                )
            if epoch is not None and epoch <= applier.epoch:
                raise MetricsTPUUserError(
                    f"promote(epoch={epoch}): the fencing epoch must exceed the applied "
                    f"lineage epoch ({applier.epoch}) — a stale lease cannot depose its successor"
                )
            # 1. stop the poll thread, then drain what was already shipped;
            # park() makes the cutoff hard — stop()'s join can time out on a
            # poll thread wedged in a cold kernel compile, and once writable,
            # a late replay of old-primary records would mutate promoted
            # state without ever being journaled in the new lineage
            applier.stop()
            applier.drain(cfg.drain_timeout_s)
            applier.park()
            # 2. fence: from this instant the old epoch is dead at the boundary
            new_epoch = applier.epoch + 1 if epoch is None else int(epoch)
            cfg.transport.fence(new_epoch)
            with self._lock:
                self._repl_epoch = new_epoch
                self._repl_follower = False
            # 3. own lineage: fresh WAL numbering + a synchronous pin snapshot
            # (without it, a crash before the first periodic snapshot would
            # replay the new WAL onto an EMPTY state)
            self._wal_seq = -1
            try:
                self._open_promoted_lineage(cfg)
            except Exception as exc:  # noqa: BLE001 — promotion must stay exception-safe:
                # the state flip (fence, _repl_follower) already happened, and
                # the failover hook absorbs raises — failing HERE without
                # starting the dispatcher would leave a half-promoted engine
                # that accepts submits nothing ever drains, with the
                # idempotency guard blocking every retry. An unopenable
                # lineage (unwritable/full directory) degrades to serving
                # WITHOUT durability instead — loud, available, recoverable.
                self._ckpt_writer = None
                self._journal = None
                self._wal_seq = -1
                warnings.warn(
                    f"promote(): opening the promote_checkpoint lineage failed "
                    f"({type(exc).__name__}: {exc}) — the promoted primary is serving "
                    "WITHOUT durability",
                    RuntimeWarning,
                    stacklevel=2,
                )
            # 3b. re-ship: the new primary publishes its lineage to the
            # surviving peers over the transport the caller wired (fan-out in
            # a cluster). Without a journal there is nothing to ship — the
            # config is still installed so health() reports the role honestly.
            if ship is not None:
                self._repl_cfg = ship
                if self._journal is not None:
                    self._shipper = Shipper(
                        ship,
                        store=self._ckpt_store,
                        journal=self._journal,
                        telemetry=self.telemetry,
                        engine_label=self.telemetry.engine_id,
                        epoch=self._repl_epoch,
                    )
                else:
                    warnings.warn(
                        "promote(ship=...): no WAL journal after promotion (missing or "
                        "failed promote_checkpoint lineage) — the promoted primary "
                        "cannot ship to its followers",
                        RuntimeWarning,
                        stacklevel=2,
                    )
            # 4. writable
            self.start()
        self.telemetry.count("promotions")
        _obs.record_repl_promotion(self.telemetry.engine_id)
        self._publish_health()

    def _open_promoted_lineage(self, cfg: ReplConfig) -> None:
        """Promotion step 3: the node's OWN durable plane + pin snapshot."""
        if cfg.promote_checkpoint is None:
            warnings.warn(
                "promote(): no ReplConfig.promote_checkpoint lineage configured — the "
                "promoted primary is serving WITHOUT durability",
                RuntimeWarning,
                stacklevel=3,
            )
            return
        from dataclasses import replace as _dc_replace

        self._init_checkpoint(_dc_replace(cfg.promote_checkpoint, resume=False))
        if self._journal is not None:
            # the directory may not be fresh: a node promoted ONCE, dead,
            # re-attached as follower and promoted AGAIN with the same static
            # config re-opens its old lineage's journal, which continues
            # numbering past the leftover segments. Anchor at the re-opened
            # tail — the pin below then covers every stale record, so a later
            # recovery replays only THIS incarnation's writes (starting from
            # -1 would replay the dead incarnation's records 0..k on top of
            # the pinned state, silently corrupting every touched tenant),
            # and rotation GC's the stale segments.
            self._wal_seq = int(self._journal.last_seq)
        self._ckpt_writer.checkpoint_sync(self._checkpoint_view)

    def demote(self, replication: Optional[ReplConfig] = None) -> None:
        """Primary → follower step-down: the mirror of :meth:`promote`.

        The cluster plane calls this when a leader loses its lease (or a
        follower must re-attach to a new leader's ship link). Order matters —
        refuse new writes FIRST (submits raise
        :class:`~metrics_tpu.repl.errors.NotPrimaryError` from the instant the
        flag flips), then drain what was already accepted into the old lineage
        (acked work is never dropped), stop the dispatcher and shipper, release
        the durable plane (a follower does not own a lineage — the invariant
        ``__init__`` enforces), and finally either attach the new follow link
        (``replication`` = a ``role="follower"`` ReplConfig) or park read-only
        and unattached (``replication=None`` — safe to call before a successor
        even exists; the node rejoins a lineage via a later ``demote(cfg)``).

        Safe on an engine that is already a follower: the write-path teardown
        is skipped and only the link swap runs (re-attach). The old transport
        is NOT fenced here — fencing belongs to the successor's promotion.
        """
        if replication is not None and replication.role != "follower":
            raise MetricsTPUUserError(
                f"demote() takes replication=None or a role='follower' ReplConfig, "
                f"got role={replication.role!r}"
            )
        with self._promote_lock:
            # 1. refuse new writes before anything else: a deposed leader that
            # keeps accepting submits races its successor (they would die at
            # the transport fence, but refusing them at the door is cheaper
            # and honest to the caller)
            with self._lock:
                self._repl_follower = True
                self._not_empty.notify_all()
            # 2. drain accepted work into the old lineage, then retire the
            # dispatcher (bounded: a step-down must not hang on a wedged engine)
            drain_s = (
                replication.drain_timeout_s
                if replication is not None
                else (self._repl_cfg.drain_timeout_s if self._repl_cfg is not None else 5.0)
            )
            worker = self._worker
            if worker is not None and not self._quarantined:
                try:
                    self.flush(timeout=drain_s)
                except TimeoutError:
                    warnings.warn(
                        f"demote(): drain did not complete within {drain_s}s — "
                        "unfinished accepted work is abandoned with the old lineage",
                        RuntimeWarning,
                        stacklevel=2,
                    )
            if worker is not None:
                with self._lock:
                    self._worker_epoch += 1
                    self._worker = None
                    self._not_empty.notify_all()
                if worker is not threading.current_thread():
                    worker.join(timeout=5.0)
            # 3. shipper: close() makes one final publish, so the drained tail
            # reaches the followers before the link goes quiet (a fence by the
            # successor is absorbed — its lineage already superseded ours)
            if self._shipper is not None:
                self._shipper.close()
                self._shipper = None
            # 4. old follow link, if any (re-attach replaces it wholesale)
            if self._applier is not None:
                self._applier.stop()
                self._applier = None
            # 5. release the durable plane: the lineage stays on disk for the
            # successor's history, but this node no longer owns or extends it
            if self._ckpt_writer is not None:
                self._ckpt_writer.close()
                self._ckpt_writer = None
            if self._journal is not None:
                self._journal.close()
                self._journal = None
            self._ckpt_store = None
            self._ckpt_cfg = None
            self._wal_seq = -1
            self._wal_error = None
            self._wal_slots_sent = set()
            self._snapshot_seqs = {}
            # 6. attach the new link, or park read-only/unattached
            if replication is not None:
                self._repl_cfg = replication
                self._applier = ReplicaApplier(
                    self,
                    replication,
                    telemetry=self.telemetry,
                    engine_label=self.telemetry.engine_id,
                )
        self.telemetry.count("demotions")
        self._publish_health()

    def _replication_health(self) -> Dict[str, Any]:
        info: Dict[str, Any] = {
            "role": "follower" if self._repl_follower else "primary",
            "epoch": self._repl_epoch,
        }
        shipper, applier = self._shipper, self._applier
        if shipper is not None:
            info["shipped_seq"] = shipper.last_shipped_seq
            info["shipped_generation"] = shipper.shipped_generation
            info["fenced"] = shipper.fenced
            info["ship_failures"] = shipper.ship_failures
            # a spooling transport (DirectoryTransport) that hit its spool cap
            # dropped frames the follower must re-bootstrap past — surface it
            # next to the failure count it usually explains
            spool_dropped = getattr(shipper.transport, "spool_dropped", None)
            if spool_dropped is not None:
                info["spool_dropped"] = spool_dropped
            err = shipper.last_error
            info["ship_error"] = None if err is None else f"{type(err).__name__}: {err}"
        if applier is not None:
            info["applied_seq"] = applier.applied_seq
            info["known_seq"] = applier.known_seq
            info["bootstrapped"] = applier.bootstrapped
            err = applier.last_error
            info["apply_error"] = None if err is None else f"{type(err).__name__}: {err}"
            if self._repl_follower:
                lag = applier.lag()
                info["lag_seqs"] = lag.seqs_behind
                info["lag_seconds"] = lag.seconds_behind
        return info

    def _run(self, epoch: int = 0) -> None:
        detector = self._hang_detector
        backlog = self._guard.backlog if self._guard is not None else None
        while True:
            with self._not_empty:
                while (
                    not self._queue
                    and not (backlog is not None and backlog.count)
                    and not self._closed
                    and self._worker_epoch == epoch
                ):
                    self._not_empty.wait(0.1)
                if self._worker_epoch != epoch:
                    return  # superseded while idle: a fresh generation owns the queue
                if not self._queue and not (backlog is not None and backlog.count) and self._closed:
                    return
                if self._guard is not None:
                    # arrival queue moves into the guard's persistent fair
                    # backlog; selection costs O(quantum), never O(backlog)
                    batch, rejected = self._guard.form_drain(self._queue)
                    self._queue = []
                else:
                    batch, rejected = self._queue, []
                    self._queue = []
                self._inflight = len(batch)
                # a hang takeover replays exactly this list (minus resolved futures)
                self._active_batch = batch
                self.telemetry.gauge_queue_depth(backlog.count if backlog is not None else 0)
                self._not_full.notify_all()
                if not batch and not self._queue and not (backlog is not None and backlog.count):
                    self._idle.notify_all()
            if detector is not None:
                detector.mark_busy()
            if _OBS.enabled and batch:
                # backlog segment boundary for traced requests: the instant
                # the drain pulled them out of queue/backlog residency
                t_drain = time.perf_counter()
                for req in batch:
                    if req.ctx is not None:
                        req.t_drain = t_drain
            # fail expired/shed requests fast, outside the engine lock (future
            # callbacks run arbitrary user code)
            for req, exc in rejected:
                self.telemetry.count("failed")
                req.future.set_exception(exc)
            if not batch:
                if detector is not None:
                    detector.mark_idle()
                continue
            self._worker_gate.wait()
            with self._lock:
                if self._worker_epoch != epoch:
                    return  # declared hung at the gate: the handler owns the batch now
            try:
                self._process(batch, epoch)
                with self._lock:
                    if self._worker_epoch != epoch:
                        return  # superseded mid-batch: the handler owns accounting
                    self._active_batch = None
                    self._inflight = 0
                    self._idle.notify_all()
                self._maybe_checkpoint()
                self._maybe_tier()
                if detector is not None:
                    detector.mark_idle()
            except _WorkerSuperseded:
                return
            except BaseException as exc:  # noqa: BLE001 — dispatcher death: degrade, don't lose work
                self._on_worker_death(exc, batch, epoch)
                return

    def _check_epoch(self, epoch: Optional[int]) -> None:
        if epoch is not None and self._worker_epoch != epoch:
            raise _WorkerSuperseded()

    def _process(self, batch: List[_Request], epoch: Optional[int] = None) -> None:
        if self._fused:
            try:
                self._process_fused(batch, epoch)
                return
            except _FusedUnsupported:
                pass
            # A trace failure is ambiguous: the metric's update may be untraceable
            # (demote permanently), or ONE malformed request may have poisoned its
            # chunk's trace (reject that request, keep the fused path for everyone
            # else). The eager retry distinguishes them: it re-runs the same updates
            # outside the trace, so a malformed request fails ITS future there while
            # an untraceable-but-valid update succeeds for every request.
            remaining = [req for req in batch if not req.future.done()]
            self._process_eager(remaining, epoch)
            if remaining and all(req.future.exception() is None for req in remaining):
                self._demote_to_eager()
            return
        self._process_eager([req for req in batch if not req.future.done()], epoch)

    # ---------------------------------------------------- fused (bucketed) dispatch

    def _process_fused(self, batch: List[_Request], epoch: Optional[int] = None) -> None:
        with self._dispatch_lock:
            # re-validate the generation under the lock a hang takeover must
            # acquire before replaying: a superseded worker never dispatches
            self._check_epoch(epoch)
            if self._tier is not None:
                # slot revalidation: a request's slot was resolved at submit
                # time, outside this lock — the tenant may have been demoted
                # (slot freed, possibly reused) or was non-resident to begin
                # with (slot None). Re-resolve every slot here, readmitting
                # non-resident tenants right before the micro-batch that
                # needs their rows. The lower-tier check comes BEFORE the slot
                # table, same as _resolve_slot: a submit racing a demotion can
                # allocate a fresh slot for a key whose captured state sits in
                # the warm mirror, and promotion must restore that state over
                # the freshly-init row. The loop runs once per dispatched
                # request with the whole engine waiting on it (the tier <5%
                # overhead gate), hence local bindings instead of method calls.
                tier = self._tier
                warm, cold = tier.warm, tier.cold
                keyed = self._keyed
                slots = keyed._slots if isinstance(keyed, KeyedState) else None
                heat = tier._heat if self._tier_policy else None
                clock = tier.cfg.clock
                for req in batch:
                    if req.future.done():
                        continue
                    key = req.key
                    if key in warm or key in cold:
                        req.slot = self._promote_tenant(key)
                    elif slots is not None:
                        slot = slots.get(key)
                        req.slot = slot if slot is not None else keyed.slot_for(key)
                    else:
                        req.slot = keyed.slot_for(key)
                    if heat is not None:
                        heat[key] = clock()
            if self._keyed.ensure_capacity():
                self.telemetry.count("key_growths")
                self.telemetry.observe_resize(self._keyed.last_resize_s)
            for signature, reqs in self._signature_groups(batch):
                self._dispatch_group(signature, reqs)

    @staticmethod
    def _signature_groups(batch: List[_Request]) -> List[Tuple[Signature, List[_Request]]]:
        """Coalesce the drained batch into dispatch groups of one shape signature.

        Batch-wide grouping maximizes bucket occupancy but replays a tenant's
        requests signature-by-signature, which reorders them when ONE tenant mixes
        shapes in the same drain. Per-tenant submission order is part of the engine's
        sequential-semantics contract, so that (rare) case falls back to grouping by
        consecutive same-signature runs — order-preserving, slightly smaller
        micro-batches."""
        tenant_sig: Dict[Hashable, Signature] = {}
        mixed = False
        for req in batch:
            prev = tenant_sig.setdefault(req.key, req.signature)
            if prev != req.signature:
                mixed = True
                break
        groups: List[Tuple[Signature, List[_Request]]] = []
        if not mixed:
            by_sig: Dict[Signature, List[_Request]] = {}
            for req in batch:
                by_sig.setdefault(req.signature, []).append(req)
            groups.extend(by_sig.items())
        else:
            for req in batch:
                if groups and groups[-1][0] == req.signature:
                    groups[-1][1].append(req)
                else:
                    groups.append((req.signature, [req]))
        return groups

    def _dispatch_group(self, signature: Signature, reqs: List[_Request]) -> None:
        # expand oversized requests into row-chunks, then greedily pack chunks into
        # micro-batches of at most max_rows rows
        units: List[Tuple[_Request, Tuple[Any, ...], int, bool]] = []
        for req in reqs:
            chunks = split_rows(req.args, self._max_rows)
            for i, (chunk_args, rows) in enumerate(chunks):
                units.append((req, chunk_args, rows, i == len(chunks) - 1))

        pending: List[Tuple[_Request, Tuple[Any, ...], int, bool]] = []
        pending_rows = 0
        for unit in units:
            if pending and pending_rows + unit[2] > self._max_rows:
                self._dispatch_chunk(signature, pending, pending_rows)
                pending, pending_rows = [], 0
            pending.append(unit)
            pending_rows += unit[2]
        if pending:
            self._dispatch_chunk(signature, pending, pending_rows)

    def _dispatch_chunk(
        self,
        signature: Signature,
        units: List[Tuple[_Request, Tuple[Any, ...], int, bool]],
        total_rows: int,
    ) -> None:
        bucket = choose_bucket(total_rows, self._buckets)
        if (
            self._guard is not None
            and (signature, bucket, self._keyed.capacity) not in self._kernels
            and not self._guard.allow_compile()
        ):
            # compile breaker open: a novel signature would grow the compile
            # cache — run this chunk eagerly instead. Cached kernels keep
            # serving everyone else at full speed; the signature sprayer pays
            # with its own latency.
            self._apply_chunk_eager(units)
            return
        # traced contexts this micro-batch coalesced (deduped — a request split
        # into several row-chunks packed into one micro-batch links once)
        traced: List[_TraceContext] = []
        if _OBS.enabled:
            seen_spans: set = set()
            for req, _, _, _ in units:
                rctx = req.ctx
                if rctx is not None and rctx.span_id not in seen_spans:
                    seen_spans.add(rctx.span_id)
                    traced.append(rctx)
        with _obs.engine_span(
            "engine.batch", bucket=bucket, rows=total_rows, n_units=len(units)
        ) as bspan:
            if traced:
                # THE batch↔request link: one batch span naming every request
                # context it coalesced (trace ids, comma-joined)
                bspan.set_attr(
                    traces=",".join(c.trace_hex for c in traced), linked=len(traced)
                )
            kernel = self._get_kernel(signature, bucket, self._keyed.capacity)
            columns, key_ids, mask = pad_micro_batch(
                [(req.slot, chunk_args, rows) for req, chunk_args, rows, _ in units], bucket
            )
            t_k0 = time.perf_counter() if traced else 0.0
            with _obs.engine_span("engine.dispatch", bucket=bucket, rows=total_rows):
                self._keyed.stacked = kernel(self._keyed.stacked, key_ids, mask, *columns)
                # commit before completing futures: surfaces device-side errors here and
                # makes the receipt mean "your rows are in the state", not "your rows are
                # enqueued"
                jax.block_until_ready(self._keyed.stacked)
            t_k1 = time.perf_counter() if traced else 0.0
            # WAL after commit, before acks: an acknowledged chunk is always
            # replayable, and a chunk whose trace failed is never journaled
            if self._journal is not None:
                self._journal_chunk(units, key_ids, mask, columns, traced)
            t_j = time.perf_counter() if traced else 0.0
        self.telemetry.observe_batch(total_rows, bucket)
        now = time.perf_counter()
        for req, _, rows, is_last in units:
            req.rows_done += rows
            if not is_last:
                continue
            self.telemetry.count("processed")
            self.telemetry.observe_latency(now - req.t_submit)
            if traced and req.ctx is not None:
                self._emit_request_span(req, bucket, t_k0, t_k1, t_j, now)
            req.future.set_result({"key": req.key, "rows": req.rows, "bucket": bucket})
            if self._guard is not None and self._guard._quarantine_entries:
                # successes only matter to tenants with a live failure ledger
                self._guard.on_request_outcome(req.key, True)

    def _emit_request_span(
        self, req: _Request, bucket: int, t_k0: float, t_k1: float, t_j: float, now: float
    ) -> None:
        """One retrospective ``engine.request`` span per traced request,
        emitted at resolution time: its duration is the client-observed
        latency (submit entry → future resolution) and its attrs decompose it
        into admission/backlog/dispatch/kernel/journal segments that partition
        submit→journal-end exactly — the residue vs the span's own duration is
        just the resolution loop itself (the ≥95% trace-test criterion)."""
        ctx = req.ctx
        t_admitted = req.t_admitted or req.t_submit
        t_drain = req.t_drain or t_admitted
        _TRACER.record_span(
            "engine.request",
            int(req.t_submit * 1e9),
            int((now - req.t_submit) * 1e9),
            parent="engine.batch",
            trace=ctx.trace_hex,
            span=ctx.span_hex,
            bucket=bucket,
            rows=req.rows,
            admission_s=t_admitted - req.t_submit,
            backlog_s=t_drain - t_admitted,
            dispatch_s=t_k0 - t_drain,
            kernel_s=t_k1 - t_k0,
            journal_s=t_j - t_k1,
            total_s=now - req.t_submit,
        )

    def _apply_chunk_eager(self, units: List[Tuple[_Request, Tuple[Any, ...], int, bool]]) -> None:
        """Apply one chunk's rows eagerly under the dispatch lock (compile breaker
        open): whole-chunk ``update_state`` per request — the same semantics as the
        eager/inline path, journaled the same way (one 'R' record per chunk) so a
        replay reproduces exactly what was applied."""
        for req, chunk_args, rows, is_last in units:
            if req.future.done():
                continue  # an earlier chunk of this request already failed it
            try:
                if self._journal is not None:
                    self._journal_append(
                        [_encode_request_record(self._key_bytes(req.key), chunk_args, req.ctx)]
                    )
                self._keyed.ensure_capacity()
                state = self._keyed.state_of(req.key)
                self._keyed.set_state(req.key, self._metric.update_state(state, *chunk_args))
            except Exception as exc:  # noqa: BLE001 — fail THIS request, keep serving
                self.telemetry.count("failed")
                req.future.set_exception(exc)
                if self._guard is not None:
                    self._guard.on_request_outcome(req.key, False)
                continue
            req.rows_done += rows
            if not is_last:
                continue
            self.telemetry.count("processed")
            self.telemetry.observe_latency(time.perf_counter() - req.t_submit)
            req.future.set_result({"key": req.key, "rows": req.rows, "bucket": None})
            if self._guard is not None:
                self._guard.on_request_outcome(req.key, True)

    def _get_kernel(self, signature: Signature, bucket: int, capacity: int) -> Callable:
        cache_key = (signature, bucket, capacity)
        kernel = self._kernels.get(cache_key)
        if kernel is None:
            # kernel-cache miss == one fresh XLA compile: attribute it to the
            # request signature that caused it (obs retrace attribution)
            if _OBS.enabled:
                _obs.record_engine_compile(signature, bucket, capacity)
            kernel = self._build_kernel()
            self._kernels[cache_key] = kernel
        return kernel

    def _build_kernel(self) -> Callable:
        """One jitted micro-batch kernel: masked per-row scan over the stacked state.

        The scan body runs the metric's own ``update_state`` on the addressed tenant's
        slice — sequential per-tenant semantics, one XLA dispatch for the whole
        micro-batch across all tenants. The body is the kernel plane's
        ``engine_masked_scan`` entry (metrics_tpu/kernels/engine_scan.py): the
        reference `where`-selects the pre-update state back for masked (padding)
        rows before scattering; the fused lowering — selected per the registry
        mode, statically per compiled kernel — folds the mask into the scatter
        address instead (masked rows land in a scratch row sliced off at exit),
        one pass over the tenant slice per row and bit-identical on real rows.
        The input stack is donated: the engine owns it exclusively, so XLA can
        update the buffers in place on the reference path.
        """
        from metrics_tpu.kernels.engine_scan import masked_scan_update

        metric = self._metric
        telemetry = self.telemetry

        def kernel(stacked: Any, key_ids: jax.Array, mask: jax.Array, *columns: jax.Array) -> Any:
            # executes at trace time only — counts actual recompiles, not calls
            telemetry.count("compiles")
            return masked_scan_update(metric.update_state, stacked, key_ids, mask, columns)

        jitted = jax.jit(kernel, donate_argnums=0)

        def guarded(stacked: Any, key_ids: jax.Array, mask: jax.Array, *columns: jax.Array) -> Any:
            try:
                return jitted(stacked, key_ids, mask, *columns)
            except Exception as exc:  # noqa: BLE001
                # Trace/compile failures here are either an untraceable metric update
                # (TracerBoolConversionError/ConcretizationTypeError as TypeError,
                # data-dependent masking as IndexError) or ONE malformed request
                # poisoning its chunk (shape errors as TypeError/ValueError). Both
                # funnel into _process's eager retry, which re-runs the same updates
                # outside the trace — a genuine bug is re-raised on its own request's
                # future, never masked, and the dispatcher never dies on a per-chunk
                # failure.
                raise _FusedUnsupported(repr(exc)) from exc

        return guarded

    def _demote_to_eager(self) -> None:
        """Permanent fused→eager fallback: migrate accumulated stacked state."""
        with self._dispatch_lock:
            old = self._keyed
            eager = EagerKeyedState(self._metric, window=self._window)
            for key in old.keys:
                eager.slot_for(key)
                eager.set_state(key, old.state_of(key))
            if old._ring is not None and eager._ring is not None:
                for cap, snap in old._ring:
                    seg: Dict[Hashable, Any] = {}
                    for key in old.keys:
                        slot = old._slots[key]
                        if slot < cap:
                            seg[key] = jax.tree.map(lambda x: x[slot], snap)
                    eager._ring.append(seg)
            eager.rotations = old.rotations  # demoted entries still align by absolute index
            self._keyed = eager
            self._fused = False
            self._kernels.clear()
        self.telemetry.count("fused_fallbacks")

    # ---------------------------------------------------- eager / degraded dispatch

    def _process_eager(self, batch: List[_Request], epoch: Optional[int] = None) -> None:
        for req in batch:
            self._check_epoch(epoch)
            self._apply_inline(req)

    def _apply_inline(self, req: _Request) -> None:
        """Synchronous per-request dispatch (eager mode, and the degraded path).

        Applies only the rows a fused chunk has not already committed, so a request
        caught mid-demotion is never double-counted. Duplicate application from a
        hang-takeover replay racing the superseded worker is excluded UNDER the
        dispatch lock: the skip check reads ``future.done()`` *and* ``rows_done``
        (marked applied inside the lock, before resolution happens outside it), so
        two appliers serialize — the loser sees the marker and returns without
        touching state or the future.
        """
        try:
            args = req.args if req.rows_done == 0 else tuple(a[req.rows_done :] for a in req.args)
            with _obs.engine_span("engine.inline", rows=req.rows), self._dispatch_lock:
                if req.future.done() or (req.rows > 0 and req.rows_done >= req.rows):
                    return
                if self._tier is not None:
                    # readmit a non-resident tenant before touching its state;
                    # journaled (P) before the request record below, so replay
                    # restores then applies in the same order
                    self._resolve_slot(req.key)
                    if self._tier_policy:
                        self._tier.touch(req.key)
                # journal INSIDE the dispatch lock: a snapshot (same lock)
                # must never record WAL coverage of a not-yet-applied request.
                # Trimmed args keep rows already committed (and chunk-
                # journaled) out of the record
                self._journal_requests([req], args_override=args)
                if isinstance(self._keyed, EagerKeyedState):
                    self._keyed.update(req.key, *args)
                else:
                    state = self._keyed.state_of(req.key)
                    state = self._metric.update_state(state, *args)
                    self._keyed.set_state(req.key, state)
                # applied: mark before leaving the lock, so a concurrent
                # replayer can never re-apply while we resolve outside it
                req.rows_done = req.rows
        except Exception as exc:  # noqa: BLE001 — fail THIS request, keep serving
            try:
                req.future.set_exception(exc)
            except Exception:  # noqa: BLE001 — already resolved by a racing applier
                return
            self.telemetry.count("failed")
            if self._guard is not None:
                self._guard.on_request_outcome(req.key, False)
            return
        try:
            req.future.set_result({"key": req.key, "rows": req.rows, "bucket": None})
        except Exception:  # noqa: BLE001 — already resolved by a racing applier
            return
        self.telemetry.count("processed")
        if self._degraded or self._worker is None:
            # only true caller-thread dispatch counts: the healthy eager path also
            # lands here, and counting it would make a healthy engine look degraded
            self.telemetry.count("inline_dispatches")
        self.telemetry.observe_latency(time.perf_counter() - req.t_submit)
        if self._guard is not None and self._guard._quarantine_entries:
            self._guard.on_request_outcome(req.key, True)

    def _on_worker_death(self, exc: BaseException, batch: List[_Request], epoch: Optional[int] = None) -> None:
        """Dispatcher crashed: complete all accepted work inline, then degrade.

        ``_inflight`` stays equal to the unreplayed remainder throughout, so a
        concurrent ``flush()`` keeps blocking until the replay finishes — 'accepted
        implies committed after flush' holds across the degradation. With a guard
        plane configured for restarts, a fresh dispatcher is started once the
        replay completes and the engine returns to ``SERVING``.
        """
        self._worker_error = exc
        self.telemetry.count("worker_deaths")
        with self._lock:
            if epoch is not None and self._worker_epoch != epoch:
                return  # a hang takeover already owns this batch and the queue
            # supersede ourselves so a concurrent hang takeover cannot double-own
            self._worker_epoch += 1
            self._degraded = True
            self._active_batch = None
            pending = [req for req in batch if not req.future.done()] + self._queue
            if self._guard is not None:
                pending += self._guard.take_backlog()
            self._queue = []
            self._inflight = len(pending)
            self.telemetry.gauge_queue_depth(0)
            self._not_full.notify_all()
        try:
            for req in pending:
                self._apply_inline(req)
                with self._lock:
                    self._inflight -= 1
        finally:
            with self._lock:
                self._inflight = 0
                self._idle.notify_all()
            if self._hang_detector is not None:
                self._hang_detector.mark_idle()
        self._maybe_restart_worker()
        self._publish_health()

    def _on_worker_hang(self) -> None:
        """Watchdog callback: the dispatcher has been busy on one batch past the
        timeout. Supersede it (epoch bump) and decide by probing the dispatch lock:

        - lock acquirable within ``hang_lock_timeout_s`` → the worker is stuck
          *outside* the device path (and can never dispatch again: it re-checks
          its epoch under this very lock). Replay the taken-over batch + queue
          inline — the existing flush-correct worker-death ladder — then
          restart a fresh dispatcher if configured.
        - lock NOT acquirable → the worker is wedged inside a device call;
          replaying would risk double-commit if the call ever completes.
          QUARANTINE the engine: fail every pending future fast and reject all
          further calls instead of hanging clients on a dead device.
        """
        with self._lock:
            if self._closed or self._degraded or self._quarantined:
                return
            if self._active_batch is None and not self._queue and not self._guard.backlog.count:
                return  # raced with batch completion: nothing is actually stuck
            self._worker_epoch += 1
            self._degraded = True  # submits go inline while we sort this out
            batch = self._active_batch or []
            self._active_batch = None
            pending = [req for req in batch if not req.future.done()] + self._queue
            pending += self._guard.take_backlog()
            self._queue = []
            self._inflight = len(pending)
            self.telemetry.gauge_queue_depth(0)
            self._not_full.notify_all()
        self.telemetry.count("worker_hangs")
        self._worker_error = TimeoutError(
            f"dispatcher hung: busy past the {self._guard.cfg.watchdog_timeout_s}s watchdog timeout"
        )
        timeout = self._guard.cfg.hang_lock_timeout_s
        if not self._dispatch_lock.acquire(timeout=timeout):
            self._quarantine_engine(pending)
            return
        self._dispatch_lock.release()
        try:
            for req in pending:
                self._apply_inline(req)
                with self._lock:
                    self._inflight -= 1
        finally:
            with self._lock:
                self._inflight = 0
                self._idle.notify_all()
            if self._hang_detector is not None:
                self._hang_detector.mark_idle()
        self._maybe_restart_worker()
        self._publish_health()

    def _quarantine_engine(self, pending: List[_Request]) -> None:
        """The wedged worker cannot be taken over safely: fail fast from now on."""
        with self._lock:
            self._quarantined = True
            self._not_full.notify_all()
        exc = EngineQuarantined(
            "StreamingEngine quarantined: dispatcher wedged in a device call; "
            "request not committed"
        )
        for req in pending:
            if not req.future.done():
                self.telemetry.count("failed")
                req.future.set_exception(exc)
            if req.is_probe and self._guard is not None:
                self._guard.abandon_probe(req.key)
        with self._lock:
            self._inflight = 0
            self._idle.notify_all()
        if self._hang_detector is not None:
            self._hang_detector.mark_idle()
        self._publish_health()

    def _maybe_restart_worker(self) -> None:
        """Start a fresh dispatcher after a death/hang takeover, budget permitting."""
        guard = self._guard
        if guard is None or not guard.cfg.restart:
            return
        with self._lock:
            if self._closed or self._quarantined:
                return
            if self._worker_restarts >= guard.cfg.max_restarts:
                return  # stay degraded-inline: restart storms help nobody
            self._worker_restarts += 1
            self._degraded = False
            self._spawn_worker()
        self.telemetry.count("watchdog_restarts")
        _obs.record_guard_event(guard._engine_label, "watchdog_restarts")
