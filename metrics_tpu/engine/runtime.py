"""StreamingEngine — async micro-batched, multi-tenant metric serving runtime.

The pure-functional core (``Metric.update_state`` / ``compute_from`` /
``merge_states``) is the substrate: state is an explicit pytree, updates are pure and
jittable, so a serving process does not have to serialize clients through a lock or
pay one dispatch per request. Instead:

    client threads ── submit(key, *arrays) ──► bounded queue ──► dispatcher thread
        │                                         │ coalesce + shape-bucket (bucketing.py)
        │  Future (receipt)  ◄─────────────────── │ ONE jitted donated-buffer dispatch
        │                                         ▼ per bucket: masked scan over rows,
        └── compute(key) ◄── flush ── keyed stacked state (stream.py), all tenants

Dispatch semantics are **per-row streaming updates in submission order**: the bucket
kernel scans the coalesced rows, applying the metric's own ``update_state`` to each
tenant's slice and masking padded rows back to their pre-update state. For the
engine's supported metric class (fixed-shape array states — every sum/count/extreme
accumulator) this is exactly the sequential per-request semantics, bit-for-bit; the
compile cache is bounded by ``len(buckets) × log2(tenant capacity)`` kernels PER
request signature (trailing shape + canonical dtype — a serving deployment has a
small fixed set of these; dtypes are canonicalized so numpy/jnp clients share
kernels).

Degradation ladder (each step is correctness-preserving, only slower):

1. fused micro-batched dispatch (the hot path);
2. metrics whose update cannot trace (ragged "cat" states, host-compute, data-dependent
   Python) demote permanently to eager per-request ``update_state`` on the dispatcher
   thread — still async, still multi-tenant;
3. if the dispatcher thread itself dies, the engine completes its in-flight work
   synchronously and every later ``submit`` runs inline on the caller's thread
   (per-call dispatch) — no request is ever silently lost.

Backpressure at a full queue follows ``policy``: ``"block"`` (wait for space),
``"drop"`` (raise :class:`EngineBackpressure` immediately), ``"timeout"`` (wait up to
``submit_timeout`` seconds, then raise).
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Callable, Dict, Hashable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from metrics_tpu.ckpt.store import RequestJournal, SnapshotStore
from metrics_tpu.ckpt.writer import AsyncCheckpointer
from metrics_tpu.collections import MetricCollection
from metrics_tpu.engine.bucketing import (
    DEFAULT_BUCKETS,
    Signature,
    choose_bucket,
    inspect_request,
    normalize_buckets,
    pad_micro_batch,
    split_rows,
)
from metrics_tpu.engine.stream import EagerKeyedState, KeyedState
from metrics_tpu.engine.telemetry import EngineTelemetry
from metrics_tpu.metric import Metric
from metrics_tpu.obs import instrument as _obs
from metrics_tpu.obs.registry import OBS as _OBS
from metrics_tpu.parallel.sync import sync_state_host
from metrics_tpu.utils.exceptions import MetricsTPUUserError

_POLICIES = ("block", "drop", "timeout")
_WAL_FLUSH = ("none", "flush", "fsync")

# WAL record encoding. Two record types, hand-rolled rather than pickled
# because encoding rides the dispatcher's critical path and per-request
# np.ndarray pickling alone would blow the <5% checkpoint-overhead gate:
#
# - b"C" CHUNK records — the fused hot path. One record per dispatched
#   micro-batch holding the PADDED columns + key_ids + mask exactly as the
#   kernel saw them, plus pickled key mappings for any slot ids this journal
#   has not introduced yet. Cost is a handful of ``tobytes`` calls per up-to-
#   256-row chunk (<0.1µs/request); replay walks the masked rows in scan
#   order, reproducing the kernel's per-row accumulation bit-for-bit.
# - b"R" REQUEST records — eager metrics, degraded/inline submits, and the
#   eager retry after a fused trace failure: pickled key + raw
#   dtype/shape/bytes per arg, applied whole-request on replay (matching how
#   those paths applied it originally).

_WAL_U32 = struct.Struct("<I")


def _enc_array(parts: List[bytes], a: np.ndarray) -> None:
    if a.dtype.byteorder == ">":
        a = a.astype(a.dtype.newbyteorder("="))
    name = a.dtype.name.encode()
    parts.append(bytes((len(name), a.ndim)))
    parts.append(name)
    if a.ndim:
        parts.append(struct.pack(f"<{a.ndim}q", *a.shape))
    parts.append(a.tobytes())


def _dec_array(payload: bytes, off: int) -> Tuple[np.ndarray, int]:
    from metrics_tpu.ckpt.format import _dtype_from_name

    nlen, ndim = payload[off], payload[off + 1]
    off += 2
    dtype = _dtype_from_name(payload[off : off + nlen].decode())
    off += nlen
    shape = struct.unpack_from(f"<{ndim}q", payload, off) if ndim else ()
    off += 8 * ndim
    count = int(np.prod(shape)) if ndim else 1
    arr = np.frombuffer(payload, dtype, count, off).reshape(shape)
    return arr, off + count * dtype.itemsize


def _encode_request_record(key_bytes: bytes, args: Tuple[Any, ...]) -> bytes:
    parts = [b"R", _WAL_U32.pack(len(key_bytes)), key_bytes, bytes((len(args),))]
    for a in args:
        _enc_array(parts, np.asarray(a))
    return b"".join(parts)


def _decode_request_record(payload: bytes) -> Tuple[Hashable, Tuple[Any, ...]]:
    (klen,) = _WAL_U32.unpack_from(payload, 1)
    off = 1 + _WAL_U32.size + klen
    key = pickle.loads(payload[1 + _WAL_U32.size : off])
    nargs = payload[off]
    off += 1
    args = []
    for _ in range(nargs):
        arr, off = _dec_array(payload, off)
        args.append(arr)
    return key, tuple(args)


def _encode_chunk_record(
    new_slots: List[Tuple[int, bytes]],
    key_ids: np.ndarray,
    mask: np.ndarray,
    columns: Sequence[np.ndarray],
) -> bytes:
    parts = [b"C", struct.pack("<H", len(new_slots))]
    for slot, key_bytes in new_slots:
        parts.append(_WAL_U32.pack(slot))
        parts.append(_WAL_U32.pack(len(key_bytes)))
        parts.append(key_bytes)
    parts.append(bytes((len(columns),)))
    _enc_array(parts, key_ids)
    _enc_array(parts, mask)
    for col in columns:
        _enc_array(parts, col)
    return b"".join(parts)

# Engine snapshot payload schema. Engine snapshots are operational (serving
# continuity), not archival: a version bump invalidates old generations — the
# recovery scan just skips them — rather than migrating them.
_ENGINE_SCHEMA_VERSION = 1


@dataclass(frozen=True)
class CheckpointConfig:
    """Durable-state-plane wiring for one :class:`StreamingEngine`.

    ``directory`` holds the generational snapshots AND the WAL segments. A
    background :class:`~metrics_tpu.ckpt.writer.AsyncCheckpointer` persists the
    full multi-tenant state every ``interval_s`` seconds (the dispatcher hands
    it a consistent host view between micro-batches — the submit hot path never
    blocks on IO). The WAL journals each committed fused micro-batch as ONE
    chunk record (padded columns + key ids + mask, journaled after the kernel
    commit and before the chunk's futures resolve) and each eager/inline
    request individually, so a restart recovers the newest valid snapshot and
    replays exactly the work acknowledged after it, in the original per-row
    order (see ``docs/source/persistence.md`` for the exactly-once argument).
    ``policy=None`` keeps snapshots lossless.

    ``wal_flush``: per-drained-batch durability of the journal — ``"none"``
    (OS-buffered; flushed at rotation/close), ``"flush"`` (python-level flush,
    the default), ``"fsync"`` (fsync per batch — strongest, slowest).
    """

    directory: str
    interval_s: float = 30.0
    retain: int = 3
    policy: Optional[Any] = None  # comm.CodecPolicy; None = lossless
    wal: bool = True
    wal_flush: str = "flush"
    resume: bool = True
    durable: bool = True
    rank: int = 0
    world: int = 1


class EngineClosed(MetricsTPUUserError):
    """submit() after close()."""


class EngineBackpressure(MetricsTPUUserError):
    """Request rejected at a full queue (drop policy, or timeout policy expiry)."""


class _FusedUnsupported(Exception):
    """Internal: the metric's update cannot trace inside the bucket kernel."""


class _Request:
    __slots__ = ("key", "slot", "args", "rows", "signature", "future", "t_submit", "rows_done", "seq")

    def __init__(self, key: Hashable, slot: Optional[int], args: Tuple[Any, ...],
                 rows: int, signature: Signature, future: "Future", t_submit: float) -> None:
        self.key = key
        self.slot = slot
        self.args = args
        self.rows = rows
        self.signature = signature
        self.future = future
        # stamped at submit() ENTRY, before any backpressure wait — the latency
        # percentiles must include the stall they exist to surface
        self.t_submit = t_submit
        # rows already committed to the state (fused chunks commit incrementally, so a
        # mid-batch fused→eager demotion must not re-apply them)
        self.rows_done = 0
        # WAL sequence number once journaled (None while checkpointing is off
        # or the record hasn't been appended yet) — the double-journal guard
        self.seq: Optional[int] = None


def _component_metrics(metric: Any) -> List[Metric]:
    if isinstance(metric, MetricCollection):
        return list(metric._modules.values())
    return [metric]


class StreamingEngine:
    """Serve a ``Metric`` or ``MetricCollection`` to many concurrent clients.

    Args:
        metric_or_collection: the logical metric. The engine works on a private clone,
            so the caller's instance stays free for direct use.
        buckets: micro-batch row sizes the kernels compile for (powers of two by
            default). The compile cache after warmup is bounded by this set.
        max_queue: bound on queued (not yet dispatched) requests.
        policy: backpressure policy at a full queue — "block" | "drop" | "timeout".
        submit_timeout: seconds a "timeout"-policy submit waits for queue space.
        window: sliding-window length in segments (see :meth:`rotate_window`);
            ``None`` disables windowing.
        capacity: initial tenant capacity (rounded up to a power of two; grows by
            doubling as keys arrive — each growth recompiles the bucket kernels once).
        start: launch the dispatcher thread immediately.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryAccuracy
        >>> from metrics_tpu.engine import StreamingEngine
        >>> engine = StreamingEngine(BinaryAccuracy(), buckets=(4, 8))
        >>> for preds, target in [([1, 0], [1, 1]), ([1], [1])]:
        ...     fut = engine.submit("tenant-a", jnp.array(preds), jnp.array(target))
        >>> engine.flush()
        >>> engine.compute("tenant-a")
        Array(0.6666667, dtype=float32)
        >>> engine.close()
    """

    def __init__(
        self,
        metric_or_collection: Any,
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        max_queue: int = 1024,
        policy: str = "block",
        submit_timeout: float = 1.0,
        window: Optional[int] = None,
        capacity: int = 8,
        telemetry_window: int = 2048,
        checkpoint: Optional[CheckpointConfig] = None,
        start: bool = True,
    ) -> None:
        if not isinstance(metric_or_collection, (Metric, MetricCollection)):
            raise MetricsTPUUserError(
                f"StreamingEngine serves a Metric or MetricCollection, got {type(metric_or_collection)!r}"
            )
        if policy not in _POLICIES:
            raise MetricsTPUUserError(f"`policy` must be one of {_POLICIES}, got {policy!r}")
        if max_queue < 1:
            raise MetricsTPUUserError(f"`max_queue` must be >= 1, got {max_queue}")

        self._metric = metric_or_collection.clone()
        self._buckets = normalize_buckets(buckets)
        self._max_rows = self._buckets[-1]
        self._max_queue = int(max_queue)
        self._policy = policy
        self._submit_timeout = float(submit_timeout)
        self.telemetry = EngineTelemetry(latency_window=telemetry_window)

        # Fused eligibility is structural: every component metric must hold only
        # fixed-shape array states (ragged "cat" lists cannot stack along a key axis)
        # and compute on device. Untraceable *updates* are only discoverable at trace
        # time — those demote at the first kernel build instead (telemetry
        # `fused_fallbacks`).
        self._fused = all(
            not m._host_compute and not any(isinstance(d, list) for d in m._defaults.values())
            for m in _component_metrics(self._metric)
        )
        self._keyed = (
            KeyedState(self._metric, capacity=capacity, window=window)
            if self._fused
            else EagerKeyedState(self._metric, window=window)
        )
        self._window = window

        # (signature, bucket, capacity) -> jitted kernel
        self._kernels: Dict[Tuple[Signature, int, int], Callable] = {}

        self._lock = threading.RLock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._idle = threading.Condition(self._lock)
        self._queue: List[_Request] = []
        self._inflight = 0
        self._closed = False
        self._degraded = False
        self._worker_error: Optional[BaseException] = None
        # serializes use of the private metric instance (update_state/compute_from
        # swap state attrs in and out, so two threads must not interleave there)
        self._dispatch_lock = threading.Lock()
        # test/ops hook: clearing holds the dispatcher *before* it processes a drained
        # batch, letting backpressure be exercised deterministically
        self._worker_gate = threading.Event()
        self._worker_gate.set()

        # durable state plane (None-checked on every hot path: checkpointing
        # off costs one attribute test per drained batch)
        self._ckpt_cfg: Optional[CheckpointConfig] = None
        self._ckpt_store: Optional[SnapshotStore] = None
        self._ckpt_writer: Optional[AsyncCheckpointer] = None
        self._journal: Optional[RequestJournal] = None
        self._wal_seq = -1
        self._wal_error: Optional[BaseException] = None
        self._wal_key_cache: Dict[Hashable, bytes] = {}
        self._wal_slots_sent: set = set()  # slot ids already introduced to the journal
        self._replay_slot_keys: Dict[int, Hashable] = {}
        self._snapshot_seqs: Dict[int, int] = {}  # generation -> WAL seq it covers
        if checkpoint is not None:
            self._init_checkpoint(checkpoint)

        self._worker: Optional[threading.Thread] = None
        if start:
            self.start()

    # ------------------------------------------------------------------ lifecycle

    def start(self) -> None:
        with self._lock:
            if self._worker is not None or self._closed:
                return
            self._worker = threading.Thread(
                target=self._run, name="metrics-tpu-engine-dispatch", daemon=True
            )
            self._worker.start()

    def close(self, flush: bool = True, checkpoint: bool = True) -> None:
        """Stop accepting work; by default drain what was already accepted.

        With checkpointing configured, a final snapshot is committed after the
        drain (``checkpoint=False`` skips it — the crash-simulation hook: the
        WAL then carries everything since the last periodic snapshot, exactly
        what a restart must replay).
        """
        with self._lock:
            if self._closed:
                return
        if flush:
            self.flush()
        if flush and checkpoint and self._ckpt_writer is not None:
            self._ckpt_writer.checkpoint_sync(self._checkpoint_view)
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()
            self._idle.notify_all()
            worker = self._worker
        if worker is not None and worker is not threading.current_thread():
            worker.join(timeout=10.0)
        if self._ckpt_writer is not None:
            self._ckpt_writer.close()
        if self._journal is not None:
            self._journal.close()

    def __enter__(self) -> "StreamingEngine":
        return self

    def __exit__(self, *exc_info: Any) -> None:
        self.close()

    # ------------------------------------------------------------------ client API

    def submit(self, key: Hashable, *args: Any) -> "Future":
        """Enqueue one update for tenant ``key``; resolves to a receipt dict once the
        state update has committed.

        Raises :class:`EngineBackpressure` per the configured policy when the queue is
        full, and :class:`EngineClosed` after :meth:`close`.
        """
        t_submit = time.perf_counter()
        rows, signature = inspect_request(args)
        future: Future = Future()
        with self._not_full:
            if self._closed:
                raise EngineClosed("submit() on a closed StreamingEngine")
            if self._degraded or self._worker is None:
                # synchronous per-call dispatch (dispatcher dead or never started)
                req = _Request(key, self._alloc_slot(key), tuple(args), rows, signature, future, t_submit)
                self.telemetry.count("submitted")
                self._apply_inline(req)
                return future
            deadline = time.monotonic() + self._submit_timeout
            while len(self._queue) >= self._max_queue:
                if self._policy == "drop":
                    self.telemetry.count("dropped")
                    raise EngineBackpressure(f"queue full ({self._max_queue}); request dropped")
                if self._policy == "timeout":
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self.telemetry.count("timed_out")
                        raise EngineBackpressure(
                            f"queue full ({self._max_queue}); timed out after {self._submit_timeout}s"
                        )
                    self._not_full.wait(remaining)
                else:
                    self._not_full.wait()
                if self._closed:
                    raise EngineClosed("StreamingEngine closed while waiting for queue space")
                if self._degraded:
                    req = _Request(key, self._alloc_slot(key), tuple(args), rows, signature, future, t_submit)
                    self.telemetry.count("submitted")
                    self._apply_inline(req)
                    return future
            req = _Request(key, self._alloc_slot(key), tuple(args), rows, signature, future, t_submit)
            self._queue.append(req)
            self.telemetry.count("submitted")
            self.telemetry.gauge_queue_depth(len(self._queue))
            self._not_empty.notify()
        return future

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every accepted request has committed (or ``timeout`` elapses).

        Holds through a worker death too: the death handler keeps ``_inflight`` equal
        to the number of accepted-but-unreplayed requests while it replays them
        inline, so 'accepted implies committed after flush' survives degradation.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._idle:
            while self._queue or self._inflight:
                remaining = 0.1 if deadline is None else min(0.1, deadline - time.monotonic())
                if remaining <= 0:
                    raise TimeoutError("StreamingEngine.flush timed out")
                # bounded waits double as liveness checks against a dying dispatcher
                self._idle.wait(remaining)

    def compute(self, key: Hashable, *, window: bool = False, sync: bool = False) -> Any:
        """Final metric value for tenant ``key`` (flushes first).

        ``window=True`` computes over the sliding window (requires ``window=`` at
        construction); ``sync=True`` all-reduces the state across JAX processes first
        (multi-host serving), via :func:`metrics_tpu.parallel.sync.sync_state_host`.
        """
        if window and self._window is None:
            # a silent fall-through would return unbounded lifetime accumulation
            # mislabeled as a sliding-window value
            raise MetricsTPUUserError("compute(window=True) requires the engine to be built with `window=`")
        self.flush()
        with self._dispatch_lock:
            if key not in self._keyed.keys:
                raise KeyError(f"unknown tenant key {key!r}")
            state = self._keyed.merged_state(key) if window else self._keyed.state_of(key)
            if sync:
                state = self._sync_state(state)
            return self._metric.compute_from(state)

    def compute_all(self, *, window: bool = False, sync: bool = False) -> Dict[Hashable, Any]:
        """``compute`` for every known tenant key — one flush, one consistent snapshot.

        All tenants' states are read under a single dispatch-lock acquisition after a
        single flush, so under live traffic the returned mapping is a point-in-time
        view (per-key ``compute`` in a loop would re-flush per tenant and interleave
        with new submissions).
        """
        if window and self._window is None:
            raise MetricsTPUUserError("compute_all(window=True) requires the engine to be built with `window=`")
        self.flush()
        with self._dispatch_lock:
            out: Dict[Hashable, Any] = {}
            for key in self._keyed.keys:
                state = self._keyed.merged_state(key) if window else self._keyed.state_of(key)
                if sync:
                    state = self._sync_state(state)
                out[key] = self._metric.compute_from(state)
            return out

    def rotate_window(self) -> None:
        """Close the current sliding-window segment for ALL tenants (flushes first)."""
        self.flush()
        with self._dispatch_lock:
            self._keyed.rotate()
        self.telemetry.count("window_rotations")

    def reset(self) -> None:
        """Drop all tenant state (keys stay allocated)."""
        self.flush()
        with self._dispatch_lock:
            self._keyed.reset()

    @property
    def fused(self) -> bool:
        """True while the engine serves via the single-dispatch bucket kernels."""
        return self._fused

    @property
    def degraded(self) -> bool:
        """True once the dispatcher died and submits run inline."""
        return self._degraded

    def telemetry_snapshot(self) -> Dict[str, Any]:
        snap = self.telemetry.snapshot()
        snap["fused"] = self._fused
        snap["degraded"] = self._degraded
        snap["tenants"] = len(self._keyed.keys)
        if self._ckpt_writer is not None:
            snap["ckpt_generation"] = self._ckpt_writer.last_generation
            snap["wal_seq"] = self._wal_seq
        return snap

    # ------------------------------------------------------------------ internals

    def _alloc_slot(self, key: Hashable) -> Optional[int]:
        return self._keyed.slot_for(key)

    def _sync_state(self, state: Any) -> Any:
        # multi-host serving rides the comm plane (codecs, coalesced transfers,
        # retry/degradation ladder) with its own site label so engine syncs are
        # attributable separately from bare sync_state_host callers
        if isinstance(self._metric, MetricCollection):
            return {
                name: sync_state_host(sub, self._metric._modules[name]._reductions, site="engine.compute")
                for name, sub in state.items()
            }
        return sync_state_host(state, self._metric._reductions, site="engine.compute")

    # ---------------------------------------------------- durable state plane

    def _init_checkpoint(self, cfg: CheckpointConfig) -> None:
        if cfg.wal_flush not in _WAL_FLUSH:
            raise MetricsTPUUserError(f"`wal_flush` must be one of {_WAL_FLUSH}, got {cfg.wal_flush!r}")
        self._ckpt_cfg = cfg
        self._ckpt_store = SnapshotStore(
            cfg.directory, retain=cfg.retain, rank=cfg.rank, world=cfg.world, durable=cfg.durable
        )
        if cfg.wal:
            self._journal = RequestJournal(cfg.directory, rank=cfg.rank, durable=cfg.durable)
        self._ckpt_writer = AsyncCheckpointer(
            self._ckpt_store,
            interval_s=cfg.interval_s,
            site="engine",
            policy=cfg.policy,
            schema_version=_ENGINE_SCHEMA_VERSION,
            on_commit=self._on_snapshot_commit,
            on_error=lambda exc: self.telemetry.count("checkpoint_failures"),
        )
        if cfg.resume:
            self._recover()

    def _on_snapshot_commit(self, generation: int, tree: Any, meta: Optional[Dict[str, Any]]) -> None:
        """Writer-thread callback: rotate the WAL past what every RETAINED
        generation covers. Rotating to the newest snapshot's seq would be
        wrong: if that file is later corrupted, recovery falls back to an
        older generation whose tail records must still be replayable — so the
        rotation point is the OLDEST retained generation's coverage."""
        self.telemetry.count("checkpoints")
        if self._journal is None:
            return
        self._snapshot_seqs[generation] = int(tree["seq"])
        retained = self._ckpt_store.generations()
        self._snapshot_seqs = {g: s for g, s in self._snapshot_seqs.items() if g in retained}
        covered = None
        for gen in retained:
            seq = self._snapshot_seqs.get(gen)
            if seq is None:
                try:  # generation committed by a previous process: read its meta
                    seq = int(self._ckpt_store.read_meta(gen).get("seq", -1))
                    self._snapshot_seqs[gen] = seq
                except Exception:  # noqa: BLE001 — unreadable: don't rotate past it
                    seq = -1
            covered = seq if covered is None else min(covered, seq)
        if covered is not None and covered >= 0:
            self._journal.rotate(covered_seq=covered)

    def _key_bytes(self, key: Hashable) -> bytes:
        key_bytes = self._wal_key_cache.get(key)
        if key_bytes is None:
            key_bytes = self._wal_key_cache[key] = pickle.dumps(
                key, protocol=pickle.HIGHEST_PROTOCOL
            )
        return key_bytes

    def _journal_append(self, payloads: List[bytes]) -> Optional[List[int]]:
        """Append + flush per policy; a journal IO failure disables the WAL
        (counted, remembered) instead of failing serving — durability degrades,
        availability does not."""
        try:
            seqs = self._journal.append_many(payloads)
            flush = self._ckpt_cfg.wal_flush
            if flush != "none":
                self._journal.flush(fsync=flush == "fsync")
        except Exception as exc:  # noqa: BLE001
            self._wal_error = exc
            journal, self._journal = self._journal, None
            try:
                journal.close()  # release the fd; flush whatever still can be
            except Exception:  # noqa: BLE001 — already in the failure path
                pass
            self.telemetry.count("checkpoint_failures")
            return None
        self._wal_seq = max(self._wal_seq, seqs[-1])
        self.telemetry.count("wal_records", len(payloads))
        return seqs

    def _journal_chunk(
        self,
        units: List[Tuple[_Request, Tuple[Any, ...], int, bool]],
        key_ids: Any,
        mask: Any,
        columns: Sequence[Any],
    ) -> None:
        """Journal one committed fused micro-batch as a single chunk record.

        Called AFTER the kernel committed and BEFORE the chunk's futures
        resolve: an acknowledged request is always either in a snapshot or
        replayable, and a chunk that failed to trace is never journaled (its
        eager retry journals per-request instead — no double entry). Replay
        reapplies the masked rows in scan order, so a snapshot at seq S plus
        records > S reproduces the lost process's state exactly once, bit-for-
        bit.
        """
        if self._journal is None:
            return
        new_slots = []
        for req, _, _, _ in units:
            if req.slot not in self._wal_slots_sent:
                self._wal_slots_sent.add(req.slot)
                new_slots.append((req.slot, self._key_bytes(req.key)))
        record = _encode_chunk_record(
            new_slots, np.asarray(key_ids), np.asarray(mask), [np.asarray(c) for c in columns]
        )
        self._journal_append([record])

    def _journal_requests(self, reqs: List[_Request], args_override: Optional[Tuple[Any, ...]] = None) -> None:
        """Per-request WAL records for the non-fused paths (eager metrics,
        degraded/inline submits, eager retry). ``args_override`` journals a
        trimmed argument view when part of the request already committed (and
        was journaled) through fused chunks."""
        if self._journal is None:
            return
        todo = [req for req in reqs if req.seq is None]
        if not todo:
            return
        payloads = [
            _encode_request_record(
                self._key_bytes(req.key), req.args if args_override is None else args_override
            )
            for req in todo
        ]
        seqs = self._journal_append(payloads)
        if seqs is not None:
            for req, seq in zip(todo, seqs):
                req.seq = seq

    def _checkpoint_view(self) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Consistent host-side snapshot tree of ALL tenant state + WAL position.

        Runs on the dispatcher thread between micro-batches (or on a quiesced
        caller thread) under the dispatch lock: jax arrays are immutable, so
        the device_get is the only copy and the submit path never stalls on it.
        """
        with self._dispatch_lock:
            keyed = self._keyed
            tree: Dict[str, Any] = {"kind": "engine", "seq": int(self._wal_seq)}
            if isinstance(keyed, KeyedState):
                tree["mode"] = "fused"
                tree["capacity"] = int(keyed.capacity)
                tree["slots"] = dict(keyed._slots)  # non-str keys -> object leaf
                tree["stacked"] = jax.device_get(keyed.stacked)
                tree["ring"] = [
                    {"capacity": int(cap), "stacked": jax.device_get(snap)}
                    for cap, snap in (keyed._ring or [])
                ]
            else:
                keys = list(keyed._states)
                tree["mode"] = "eager"
                tree["keys"] = {"values": keys}  # wrapped: keys may be non-JSON-able
                tree["states"] = [jax.device_get(keyed._states[k]) for k in keys]
                tree["ring"] = [
                    {
                        "keys": {"values": list(seg)},
                        "states": [jax.device_get(seg[k]) for k in seg],
                    }
                    for seg in (keyed._ring or [])
                ]
        meta = {"tenants": len(keyed.keys), "seq": tree["seq"]}
        return tree, meta

    def _maybe_checkpoint(self) -> None:
        if self._ckpt_writer is None:
            return
        try:
            self._ckpt_writer.maybe_checkpoint(self._checkpoint_view)
        except Exception:  # noqa: BLE001 — a snapshot failure must not kill the dispatcher
            self.telemetry.count("checkpoint_failures")

    def checkpoint_now(self) -> Optional[int]:
        """Flush, then snapshot synchronously; returns the committed generation.

        ``None`` when checkpointing is off or the write failed (the failure is
        counted and kept on ``self._ckpt_writer.last_error``, never raised).
        """
        if self._ckpt_writer is None:
            return None
        self.flush()
        return self._ckpt_writer.checkpoint_sync(self._checkpoint_view)

    def _validate_engine_snapshot(self, snap: Any) -> None:
        tree = snap.tree
        if snap.schema_version != _ENGINE_SCHEMA_VERSION:
            raise ValueError(f"engine snapshot schema v{snap.schema_version} != v{_ENGINE_SCHEMA_VERSION}")
        if not isinstance(tree, dict) or tree.get("kind") != "engine":
            raise ValueError("not an engine snapshot")
        mode = tree.get("mode")
        ref_leaves, ref_def = jax.tree_util.tree_flatten(self._metric.init_state())
        if mode == "fused":
            if not isinstance(self._keyed, KeyedState):
                raise ValueError("fused snapshot but the live engine serves eagerly")
            cap = int(tree["capacity"])
            for entry in [tree] + list(tree.get("ring", [])):
                leaves, treedef = jax.tree_util.tree_flatten(entry["stacked"])
                if treedef != ref_def:
                    raise ValueError("stacked state structure does not match the live metric")
                ecap = int(entry["capacity"]) if "capacity" in entry else cap
                for ref, got in zip(ref_leaves, leaves):
                    if np.dtype(got.dtype) != np.dtype(ref.dtype) or tuple(got.shape) != (ecap, *ref.shape):
                        raise ValueError(
                            f"stacked leaf {np.dtype(got.dtype).name}{tuple(got.shape)} does not match "
                            f"live {np.dtype(ref.dtype).name}{(ecap, *ref.shape)}"
                        )
        elif mode == "eager":
            ref = self._metric.init_state()
            for entry in [tree] + list(tree.get("ring", [])):
                if len(entry["keys"]["values"]) != len(entry["states"]):
                    raise ValueError("eager snapshot keys/states length mismatch")
                for st in entry["states"]:
                    # top-level key check only: ragged cat lists make full
                    # treedef comparison reject legitimate snapshots
                    if not isinstance(st, dict) or set(st) != set(ref):
                        raise ValueError("eager state structure does not match the live metric")
        else:
            raise ValueError(f"unknown engine snapshot mode {mode!r}")

    def _restore_keyed(self, tree: Dict[str, Any]) -> None:
        if tree["mode"] == "fused":
            if not isinstance(self._keyed, KeyedState):
                raise ValueError("fused snapshot but the live engine serves eagerly")
            keyed = KeyedState(self._metric, capacity=tree["capacity"], window=self._window)
            keyed.capacity = int(tree["capacity"])
            keyed.stacked = jax.tree.map(jnp.asarray, tree["stacked"])
            keyed._slots = dict(tree["slots"])
            if keyed._ring is not None:
                for entry in tree.get("ring", []):
                    keyed._ring.append(
                        (int(entry["capacity"]), jax.tree.map(jnp.asarray, entry["stacked"]))
                    )
            self._keyed = keyed
        else:
            # an eager snapshot (e.g. the crashed engine had demoted) restores
            # into a fused-capable engine by demoting it up front — recovering
            # slower always beats refusing to recover
            if not isinstance(self._keyed, EagerKeyedState):
                self._fused = False
                self._kernels.clear()
            keyed = EagerKeyedState(self._metric, window=self._window)
            keyed._states = dict(zip(tree["keys"]["values"], tree["states"]))
            if keyed._ring is not None:
                for entry in tree.get("ring", []):
                    keyed._ring.append(dict(zip(entry["keys"]["values"], entry["states"])))
            self._keyed = keyed

    def _replay_chunk(self, payload: bytes) -> None:
        """Re-apply one fused micro-batch record: masked rows in scan order."""
        off = 1
        (n_new,) = struct.unpack_from("<H", payload, off)
        off += 2
        for _ in range(n_new):
            (slot,) = _WAL_U32.unpack_from(payload, off)
            off += 4
            (klen,) = _WAL_U32.unpack_from(payload, off)
            off += 4
            self._replay_slot_keys[slot] = pickle.loads(payload[off : off + klen])
            off += klen
        ncols = payload[off]
        off += 1
        key_ids, off = _dec_array(payload, off)
        mask, off = _dec_array(payload, off)
        columns = []
        for _ in range(ncols):
            col, off = _dec_array(payload, off)
            columns.append(col)
        eager = isinstance(self._keyed, EagerKeyedState)
        for i in range(len(key_ids)):
            if not mask[i]:
                continue
            key = self._replay_slot_keys[int(key_ids[i])]
            self._keyed.slot_for(key)
            rows = tuple(col[i] for col in columns)  # (1, *trailing) — the scan slice
            if eager:
                self._keyed.update(key, *rows)
            else:
                self._keyed.ensure_capacity()
                state = self._keyed.state_of(key)
                self._keyed.set_state(key, self._metric.update_state(state, *rows))

    def _replay_request(self, key: Hashable, args: Tuple[Any, ...]) -> None:
        """Re-apply one 'R' record as ONE whole-request update — exactly how
        the eager/inline paths that produce these records applied it (fused
        work replays through chunk records instead), so float accumulation
        rounds identically to the lost process."""
        if isinstance(self._keyed, EagerKeyedState):
            self._keyed.slot_for(key)
            self._keyed.update(key, *args)
        else:
            self._keyed.slot_for(key)
            self._keyed.ensure_capacity()
            state = self._keyed.state_of(key)
            self._keyed.set_state(key, self._metric.update_state(state, *args))

    def _recover(self) -> None:
        """Restart path: newest valid snapshot + exactly-once WAL replay."""
        t0 = time.perf_counter()
        found = self._ckpt_store.latest_valid(validate=self._validate_engine_snapshot)
        if found is not None:
            gen, snap = found
            with self._dispatch_lock:
                self._restore_keyed(snap.tree)
            self._wal_seq = int(snap.tree.get("seq", -1))
            if snap.tree["mode"] == "fused":
                # chunk records reference slot ids; mappings introduced before
                # the snapshot live in rotated-away segments, so seed the
                # table from the snapshot's own slot map
                self._replay_slot_keys = {
                    slot: key for key, slot in snap.tree["slots"].items()
                }
            self.telemetry.count("recoveries")
            _obs.record_ckpt_io(
                "engine", "restore",
                os.path.getsize(self._ckpt_store.path(gen)),
                time.perf_counter() - t0, generation=gen,
            )
        if self._journal is not None:
            replayed = 0
            for seq, payload in self._journal.replay(after_seq=self._wal_seq):
                try:
                    with self._dispatch_lock:
                        if payload[:1] == b"C":
                            self._replay_chunk(payload)
                        else:
                            self._replay_request(*_decode_request_record(payload))
                except Exception:  # noqa: BLE001 — it failed when first accepted too
                    self.telemetry.count("failed")
                replayed += 1
                self._wal_seq = max(self._wal_seq, seq)
            if replayed:
                self.telemetry.count("replayed", replayed)

    def _run(self) -> None:
        while True:
            with self._not_empty:
                while not self._queue and not self._closed:
                    self._not_empty.wait(0.1)
                if not self._queue and self._closed:
                    return
                batch = self._queue
                self._queue = []
                self._inflight = len(batch)
                self.telemetry.gauge_queue_depth(0)
                self._not_full.notify_all()
            self._worker_gate.wait()
            try:
                self._process(batch)
                with self._lock:
                    self._inflight = 0
                    self._idle.notify_all()
                self._maybe_checkpoint()
            except BaseException as exc:  # noqa: BLE001 — dispatcher death: degrade, don't lose work
                self._on_worker_death(exc, batch)
                return

    def _process(self, batch: List[_Request]) -> None:
        if self._fused:
            try:
                self._process_fused(batch)
                return
            except _FusedUnsupported:
                pass
            # A trace failure is ambiguous: the metric's update may be untraceable
            # (demote permanently), or ONE malformed request may have poisoned its
            # chunk's trace (reject that request, keep the fused path for everyone
            # else). The eager retry distinguishes them: it re-runs the same updates
            # outside the trace, so a malformed request fails ITS future there while
            # an untraceable-but-valid update succeeds for every request.
            remaining = [req for req in batch if not req.future.done()]
            self._process_eager(remaining)
            if remaining and all(req.future.exception() is None for req in remaining):
                self._demote_to_eager()
            return
        self._process_eager([req for req in batch if not req.future.done()])

    # ---------------------------------------------------- fused (bucketed) dispatch

    def _process_fused(self, batch: List[_Request]) -> None:
        with self._dispatch_lock:
            if self._keyed.ensure_capacity():
                self.telemetry.count("key_growths")
            for signature, reqs in self._signature_groups(batch):
                self._dispatch_group(signature, reqs)

    @staticmethod
    def _signature_groups(batch: List[_Request]) -> List[Tuple[Signature, List[_Request]]]:
        """Coalesce the drained batch into dispatch groups of one shape signature.

        Batch-wide grouping maximizes bucket occupancy but replays a tenant's
        requests signature-by-signature, which reorders them when ONE tenant mixes
        shapes in the same drain. Per-tenant submission order is part of the engine's
        sequential-semantics contract, so that (rare) case falls back to grouping by
        consecutive same-signature runs — order-preserving, slightly smaller
        micro-batches."""
        tenant_sig: Dict[Hashable, Signature] = {}
        mixed = False
        for req in batch:
            prev = tenant_sig.setdefault(req.key, req.signature)
            if prev != req.signature:
                mixed = True
                break
        groups: List[Tuple[Signature, List[_Request]]] = []
        if not mixed:
            by_sig: Dict[Signature, List[_Request]] = {}
            for req in batch:
                by_sig.setdefault(req.signature, []).append(req)
            groups.extend(by_sig.items())
        else:
            for req in batch:
                if groups and groups[-1][0] == req.signature:
                    groups[-1][1].append(req)
                else:
                    groups.append((req.signature, [req]))
        return groups

    def _dispatch_group(self, signature: Signature, reqs: List[_Request]) -> None:
        # expand oversized requests into row-chunks, then greedily pack chunks into
        # micro-batches of at most max_rows rows
        units: List[Tuple[_Request, Tuple[Any, ...], int, bool]] = []
        for req in reqs:
            chunks = split_rows(req.args, self._max_rows)
            for i, (chunk_args, rows) in enumerate(chunks):
                units.append((req, chunk_args, rows, i == len(chunks) - 1))

        pending: List[Tuple[_Request, Tuple[Any, ...], int, bool]] = []
        pending_rows = 0
        for unit in units:
            if pending and pending_rows + unit[2] > self._max_rows:
                self._dispatch_chunk(signature, pending, pending_rows)
                pending, pending_rows = [], 0
            pending.append(unit)
            pending_rows += unit[2]
        if pending:
            self._dispatch_chunk(signature, pending, pending_rows)

    def _dispatch_chunk(
        self,
        signature: Signature,
        units: List[Tuple[_Request, Tuple[Any, ...], int, bool]],
        total_rows: int,
    ) -> None:
        bucket = choose_bucket(total_rows, self._buckets)
        kernel = self._get_kernel(signature, bucket, self._keyed.capacity)
        columns, key_ids, mask = pad_micro_batch(
            [(req.slot, chunk_args, rows) for req, chunk_args, rows, _ in units], bucket
        )
        with _obs.engine_span("engine.dispatch", bucket=bucket, rows=total_rows):
            self._keyed.stacked = kernel(self._keyed.stacked, key_ids, mask, *columns)
            # commit before completing futures: surfaces device-side errors here and
            # makes the receipt mean "your rows are in the state", not "your rows are
            # enqueued"
            jax.block_until_ready(self._keyed.stacked)
        # WAL after commit, before acks: an acknowledged chunk is always
        # replayable, and a chunk whose trace failed is never journaled
        if self._journal is not None:
            self._journal_chunk(units, key_ids, mask, columns)
        self.telemetry.observe_batch(total_rows, bucket)
        now = time.perf_counter()
        for req, _, rows, is_last in units:
            req.rows_done += rows
            if not is_last:
                continue
            self.telemetry.count("processed")
            self.telemetry.observe_latency(now - req.t_submit)
            req.future.set_result({"key": req.key, "rows": req.rows, "bucket": bucket})

    def _get_kernel(self, signature: Signature, bucket: int, capacity: int) -> Callable:
        cache_key = (signature, bucket, capacity)
        kernel = self._kernels.get(cache_key)
        if kernel is None:
            # kernel-cache miss == one fresh XLA compile: attribute it to the
            # request signature that caused it (obs retrace attribution)
            if _OBS.enabled:
                _obs.record_engine_compile(signature, bucket, capacity)
            kernel = self._build_kernel()
            self._kernels[cache_key] = kernel
        return kernel

    def _build_kernel(self) -> Callable:
        """One jitted micro-batch kernel: masked per-row scan over the stacked state.

        The scan body runs the metric's own ``update_state`` on the addressed tenant's
        slice and `where`-selects the pre-update state for masked (padding) rows, then
        scatters the slice back — sequential per-tenant semantics, one XLA dispatch for
        the whole micro-batch across all tenants. The input stack is donated: the
        engine owns it exclusively, so XLA can update the buffers in place.
        """
        metric = self._metric
        telemetry = self.telemetry

        def kernel(stacked: Any, key_ids: jax.Array, mask: jax.Array, *columns: jax.Array) -> Any:
            # executes at trace time only — counts actual recompiles, not calls
            telemetry.count("compiles")

            def step(carry: Any, xs: Tuple[Any, ...]) -> Tuple[Any, None]:
                kid, mk = xs[0], xs[1]
                rows = xs[2:]
                per_key = jax.tree.map(lambda s: s[kid], carry)
                new = metric.update_state(per_key, *rows)
                new = jax.tree.map(lambda n, o: jnp.where(mk, n, o), new, per_key)
                carry = jax.tree.map(lambda s, n: s.at[kid].set(n), carry, new)
                return carry, None

            carry, _ = lax.scan(step, stacked, (key_ids, mask, *columns))
            return carry

        jitted = jax.jit(kernel, donate_argnums=0)

        def guarded(stacked: Any, key_ids: jax.Array, mask: jax.Array, *columns: jax.Array) -> Any:
            try:
                return jitted(stacked, key_ids, mask, *columns)
            except Exception as exc:  # noqa: BLE001
                # Trace/compile failures here are either an untraceable metric update
                # (TracerBoolConversionError/ConcretizationTypeError as TypeError,
                # data-dependent masking as IndexError) or ONE malformed request
                # poisoning its chunk (shape errors as TypeError/ValueError). Both
                # funnel into _process's eager retry, which re-runs the same updates
                # outside the trace — a genuine bug is re-raised on its own request's
                # future, never masked, and the dispatcher never dies on a per-chunk
                # failure.
                raise _FusedUnsupported(repr(exc)) from exc

        return guarded

    def _demote_to_eager(self) -> None:
        """Permanent fused→eager fallback: migrate accumulated stacked state."""
        with self._dispatch_lock:
            old = self._keyed
            eager = EagerKeyedState(self._metric, window=self._window)
            for key in old.keys:
                eager.slot_for(key)
                eager.set_state(key, old.state_of(key))
            if old._ring is not None and eager._ring is not None:
                for cap, snap in old._ring:
                    seg: Dict[Hashable, Any] = {}
                    for key in old.keys:
                        slot = old._slots[key]
                        if slot < cap:
                            seg[key] = jax.tree.map(lambda x: x[slot], snap)
                    eager._ring.append(seg)
            self._keyed = eager
            self._fused = False
            self._kernels.clear()
        self.telemetry.count("fused_fallbacks")

    # ---------------------------------------------------- eager / degraded dispatch

    def _process_eager(self, batch: List[_Request]) -> None:
        for req in batch:
            self._apply_inline(req)

    def _apply_inline(self, req: _Request) -> None:
        """Synchronous per-request dispatch (eager mode, and the degraded path).

        Applies only the rows a fused chunk has not already committed, so a request
        caught mid-demotion is never double-counted.
        """
        try:
            args = req.args if req.rows_done == 0 else tuple(a[req.rows_done :] for a in req.args)
            with _obs.engine_span("engine.inline", rows=req.rows), self._dispatch_lock:
                # journal INSIDE the dispatch lock: a snapshot (same lock)
                # must never record WAL coverage of a not-yet-applied request.
                # Trimmed args keep rows already committed (and chunk-
                # journaled) out of the record
                self._journal_requests([req], args_override=args)
                if isinstance(self._keyed, EagerKeyedState):
                    self._keyed.update(req.key, *args)
                else:
                    state = self._keyed.state_of(req.key)
                    state = self._metric.update_state(state, *args)
                    self._keyed.set_state(req.key, state)
        except Exception as exc:  # noqa: BLE001 — fail THIS request, keep serving
            self.telemetry.count("failed")
            req.future.set_exception(exc)
            return
        self.telemetry.count("processed")
        if self._degraded or self._worker is None:
            # only true caller-thread dispatch counts: the healthy eager path also
            # lands here, and counting it would make a healthy engine look degraded
            self.telemetry.count("inline_dispatches")
        self.telemetry.observe_latency(time.perf_counter() - req.t_submit)
        req.future.set_result({"key": req.key, "rows": req.rows, "bucket": None})

    def _on_worker_death(self, exc: BaseException, batch: List[_Request]) -> None:
        """Dispatcher crashed: complete all accepted work inline, then degrade.

        ``_inflight`` stays equal to the unreplayed remainder throughout, so a
        concurrent ``flush()`` keeps blocking until the replay finishes — 'accepted
        implies committed after flush' holds across the degradation.
        """
        self._worker_error = exc
        self.telemetry.count("worker_deaths")
        with self._lock:
            self._degraded = True
            pending = [req for req in batch if not req.future.done()] + self._queue
            self._queue = []
            self._inflight = len(pending)
            self.telemetry.gauge_queue_depth(0)
            self._not_full.notify_all()
        try:
            for req in pending:
                self._apply_inline(req)
                with self._lock:
                    self._inflight -= 1
        finally:
            with self._lock:
                self._inflight = 0
                self._idle.notify_all()
