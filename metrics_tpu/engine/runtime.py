"""StreamingEngine — async micro-batched, multi-tenant metric serving runtime.

The pure-functional core (``Metric.update_state`` / ``compute_from`` /
``merge_states``) is the substrate: state is an explicit pytree, updates are pure and
jittable, so a serving process does not have to serialize clients through a lock or
pay one dispatch per request. Instead:

    client threads ── submit(key, *arrays) ──► bounded queue ──► dispatcher thread
        │                                         │ coalesce + shape-bucket (bucketing.py)
        │  Future (receipt)  ◄─────────────────── │ ONE jitted donated-buffer dispatch
        │                                         ▼ per bucket: masked scan over rows,
        └── compute(key) ◄── flush ── keyed stacked state (stream.py), all tenants

Dispatch semantics are **per-row streaming updates in submission order**: the bucket
kernel scans the coalesced rows, applying the metric's own ``update_state`` to each
tenant's slice and masking padded rows back to their pre-update state. For the
engine's supported metric class (fixed-shape array states — every sum/count/extreme
accumulator) this is exactly the sequential per-request semantics, bit-for-bit; the
compile cache is bounded by ``len(buckets) × log2(tenant capacity)`` kernels PER
request signature (trailing shape + canonical dtype — a serving deployment has a
small fixed set of these; dtypes are canonicalized so numpy/jnp clients share
kernels).

Degradation ladder (each step is correctness-preserving, only slower):

1. fused micro-batched dispatch (the hot path);
2. metrics whose update cannot trace (ragged "cat" states, host-compute, data-dependent
   Python) demote permanently to eager per-request ``update_state`` on the dispatcher
   thread — still async, still multi-tenant;
3. if the dispatcher thread itself dies, the engine completes its in-flight work
   synchronously and every later ``submit`` runs inline on the caller's thread
   (per-call dispatch) — no request is ever silently lost.

Backpressure at a full queue follows ``policy``: ``"block"`` (wait for space),
``"drop"`` (raise :class:`EngineBackpressure` immediately), ``"timeout"`` (wait up to
``submit_timeout`` seconds, then raise).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, Hashable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from metrics_tpu.collections import MetricCollection
from metrics_tpu.engine.bucketing import (
    DEFAULT_BUCKETS,
    Signature,
    choose_bucket,
    inspect_request,
    normalize_buckets,
    pad_micro_batch,
    split_rows,
)
from metrics_tpu.engine.stream import EagerKeyedState, KeyedState
from metrics_tpu.engine.telemetry import EngineTelemetry
from metrics_tpu.metric import Metric
from metrics_tpu.obs import instrument as _obs
from metrics_tpu.obs.registry import OBS as _OBS
from metrics_tpu.parallel.sync import sync_state_host
from metrics_tpu.utils.exceptions import MetricsTPUUserError

_POLICIES = ("block", "drop", "timeout")


class EngineClosed(MetricsTPUUserError):
    """submit() after close()."""


class EngineBackpressure(MetricsTPUUserError):
    """Request rejected at a full queue (drop policy, or timeout policy expiry)."""


class _FusedUnsupported(Exception):
    """Internal: the metric's update cannot trace inside the bucket kernel."""


class _Request:
    __slots__ = ("key", "slot", "args", "rows", "signature", "future", "t_submit", "rows_done")

    def __init__(self, key: Hashable, slot: Optional[int], args: Tuple[Any, ...],
                 rows: int, signature: Signature, future: "Future", t_submit: float) -> None:
        self.key = key
        self.slot = slot
        self.args = args
        self.rows = rows
        self.signature = signature
        self.future = future
        # stamped at submit() ENTRY, before any backpressure wait — the latency
        # percentiles must include the stall they exist to surface
        self.t_submit = t_submit
        # rows already committed to the state (fused chunks commit incrementally, so a
        # mid-batch fused→eager demotion must not re-apply them)
        self.rows_done = 0


def _component_metrics(metric: Any) -> List[Metric]:
    if isinstance(metric, MetricCollection):
        return list(metric._modules.values())
    return [metric]


class StreamingEngine:
    """Serve a ``Metric`` or ``MetricCollection`` to many concurrent clients.

    Args:
        metric_or_collection: the logical metric. The engine works on a private clone,
            so the caller's instance stays free for direct use.
        buckets: micro-batch row sizes the kernels compile for (powers of two by
            default). The compile cache after warmup is bounded by this set.
        max_queue: bound on queued (not yet dispatched) requests.
        policy: backpressure policy at a full queue — "block" | "drop" | "timeout".
        submit_timeout: seconds a "timeout"-policy submit waits for queue space.
        window: sliding-window length in segments (see :meth:`rotate_window`);
            ``None`` disables windowing.
        capacity: initial tenant capacity (rounded up to a power of two; grows by
            doubling as keys arrive — each growth recompiles the bucket kernels once).
        start: launch the dispatcher thread immediately.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryAccuracy
        >>> from metrics_tpu.engine import StreamingEngine
        >>> engine = StreamingEngine(BinaryAccuracy(), buckets=(4, 8))
        >>> for preds, target in [([1, 0], [1, 1]), ([1], [1])]:
        ...     fut = engine.submit("tenant-a", jnp.array(preds), jnp.array(target))
        >>> engine.flush()
        >>> engine.compute("tenant-a")
        Array(0.6666667, dtype=float32)
        >>> engine.close()
    """

    def __init__(
        self,
        metric_or_collection: Any,
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        max_queue: int = 1024,
        policy: str = "block",
        submit_timeout: float = 1.0,
        window: Optional[int] = None,
        capacity: int = 8,
        telemetry_window: int = 2048,
        start: bool = True,
    ) -> None:
        if not isinstance(metric_or_collection, (Metric, MetricCollection)):
            raise MetricsTPUUserError(
                f"StreamingEngine serves a Metric or MetricCollection, got {type(metric_or_collection)!r}"
            )
        if policy not in _POLICIES:
            raise MetricsTPUUserError(f"`policy` must be one of {_POLICIES}, got {policy!r}")
        if max_queue < 1:
            raise MetricsTPUUserError(f"`max_queue` must be >= 1, got {max_queue}")

        self._metric = metric_or_collection.clone()
        self._buckets = normalize_buckets(buckets)
        self._max_rows = self._buckets[-1]
        self._max_queue = int(max_queue)
        self._policy = policy
        self._submit_timeout = float(submit_timeout)
        self.telemetry = EngineTelemetry(latency_window=telemetry_window)

        # Fused eligibility is structural: every component metric must hold only
        # fixed-shape array states (ragged "cat" lists cannot stack along a key axis)
        # and compute on device. Untraceable *updates* are only discoverable at trace
        # time — those demote at the first kernel build instead (telemetry
        # `fused_fallbacks`).
        self._fused = all(
            not m._host_compute and not any(isinstance(d, list) for d in m._defaults.values())
            for m in _component_metrics(self._metric)
        )
        self._keyed = (
            KeyedState(self._metric, capacity=capacity, window=window)
            if self._fused
            else EagerKeyedState(self._metric, window=window)
        )
        self._window = window

        # (signature, bucket, capacity) -> jitted kernel
        self._kernels: Dict[Tuple[Signature, int, int], Callable] = {}

        self._lock = threading.RLock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._idle = threading.Condition(self._lock)
        self._queue: List[_Request] = []
        self._inflight = 0
        self._closed = False
        self._degraded = False
        self._worker_error: Optional[BaseException] = None
        # serializes use of the private metric instance (update_state/compute_from
        # swap state attrs in and out, so two threads must not interleave there)
        self._dispatch_lock = threading.Lock()
        # test/ops hook: clearing holds the dispatcher *before* it processes a drained
        # batch, letting backpressure be exercised deterministically
        self._worker_gate = threading.Event()
        self._worker_gate.set()

        self._worker: Optional[threading.Thread] = None
        if start:
            self.start()

    # ------------------------------------------------------------------ lifecycle

    def start(self) -> None:
        with self._lock:
            if self._worker is not None or self._closed:
                return
            self._worker = threading.Thread(
                target=self._run, name="metrics-tpu-engine-dispatch", daemon=True
            )
            self._worker.start()

    def close(self, flush: bool = True) -> None:
        """Stop accepting work; by default drain what was already accepted."""
        with self._lock:
            if self._closed:
                return
        if flush:
            self.flush()
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()
            self._idle.notify_all()
            worker = self._worker
        if worker is not None and worker is not threading.current_thread():
            worker.join(timeout=10.0)

    def __enter__(self) -> "StreamingEngine":
        return self

    def __exit__(self, *exc_info: Any) -> None:
        self.close()

    # ------------------------------------------------------------------ client API

    def submit(self, key: Hashable, *args: Any) -> "Future":
        """Enqueue one update for tenant ``key``; resolves to a receipt dict once the
        state update has committed.

        Raises :class:`EngineBackpressure` per the configured policy when the queue is
        full, and :class:`EngineClosed` after :meth:`close`.
        """
        t_submit = time.perf_counter()
        rows, signature = inspect_request(args)
        future: Future = Future()
        with self._not_full:
            if self._closed:
                raise EngineClosed("submit() on a closed StreamingEngine")
            if self._degraded or self._worker is None:
                # synchronous per-call dispatch (dispatcher dead or never started)
                req = _Request(key, self._alloc_slot(key), tuple(args), rows, signature, future, t_submit)
                self.telemetry.count("submitted")
                self._apply_inline(req)
                return future
            deadline = time.monotonic() + self._submit_timeout
            while len(self._queue) >= self._max_queue:
                if self._policy == "drop":
                    self.telemetry.count("dropped")
                    raise EngineBackpressure(f"queue full ({self._max_queue}); request dropped")
                if self._policy == "timeout":
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self.telemetry.count("timed_out")
                        raise EngineBackpressure(
                            f"queue full ({self._max_queue}); timed out after {self._submit_timeout}s"
                        )
                    self._not_full.wait(remaining)
                else:
                    self._not_full.wait()
                if self._closed:
                    raise EngineClosed("StreamingEngine closed while waiting for queue space")
                if self._degraded:
                    req = _Request(key, self._alloc_slot(key), tuple(args), rows, signature, future, t_submit)
                    self.telemetry.count("submitted")
                    self._apply_inline(req)
                    return future
            req = _Request(key, self._alloc_slot(key), tuple(args), rows, signature, future, t_submit)
            self._queue.append(req)
            self.telemetry.count("submitted")
            self.telemetry.gauge_queue_depth(len(self._queue))
            self._not_empty.notify()
        return future

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every accepted request has committed (or ``timeout`` elapses).

        Holds through a worker death too: the death handler keeps ``_inflight`` equal
        to the number of accepted-but-unreplayed requests while it replays them
        inline, so 'accepted implies committed after flush' survives degradation.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._idle:
            while self._queue or self._inflight:
                remaining = 0.1 if deadline is None else min(0.1, deadline - time.monotonic())
                if remaining <= 0:
                    raise TimeoutError("StreamingEngine.flush timed out")
                # bounded waits double as liveness checks against a dying dispatcher
                self._idle.wait(remaining)

    def compute(self, key: Hashable, *, window: bool = False, sync: bool = False) -> Any:
        """Final metric value for tenant ``key`` (flushes first).

        ``window=True`` computes over the sliding window (requires ``window=`` at
        construction); ``sync=True`` all-reduces the state across JAX processes first
        (multi-host serving), via :func:`metrics_tpu.parallel.sync.sync_state_host`.
        """
        if window and self._window is None:
            # a silent fall-through would return unbounded lifetime accumulation
            # mislabeled as a sliding-window value
            raise MetricsTPUUserError("compute(window=True) requires the engine to be built with `window=`")
        self.flush()
        with self._dispatch_lock:
            if key not in self._keyed.keys:
                raise KeyError(f"unknown tenant key {key!r}")
            state = self._keyed.merged_state(key) if window else self._keyed.state_of(key)
            if sync:
                state = self._sync_state(state)
            return self._metric.compute_from(state)

    def compute_all(self, *, window: bool = False, sync: bool = False) -> Dict[Hashable, Any]:
        """``compute`` for every known tenant key — one flush, one consistent snapshot.

        All tenants' states are read under a single dispatch-lock acquisition after a
        single flush, so under live traffic the returned mapping is a point-in-time
        view (per-key ``compute`` in a loop would re-flush per tenant and interleave
        with new submissions).
        """
        if window and self._window is None:
            raise MetricsTPUUserError("compute_all(window=True) requires the engine to be built with `window=`")
        self.flush()
        with self._dispatch_lock:
            out: Dict[Hashable, Any] = {}
            for key in self._keyed.keys:
                state = self._keyed.merged_state(key) if window else self._keyed.state_of(key)
                if sync:
                    state = self._sync_state(state)
                out[key] = self._metric.compute_from(state)
            return out

    def rotate_window(self) -> None:
        """Close the current sliding-window segment for ALL tenants (flushes first)."""
        self.flush()
        with self._dispatch_lock:
            self._keyed.rotate()
        self.telemetry.count("window_rotations")

    def reset(self) -> None:
        """Drop all tenant state (keys stay allocated)."""
        self.flush()
        with self._dispatch_lock:
            self._keyed.reset()

    @property
    def fused(self) -> bool:
        """True while the engine serves via the single-dispatch bucket kernels."""
        return self._fused

    @property
    def degraded(self) -> bool:
        """True once the dispatcher died and submits run inline."""
        return self._degraded

    def telemetry_snapshot(self) -> Dict[str, Any]:
        snap = self.telemetry.snapshot()
        snap["fused"] = self._fused
        snap["degraded"] = self._degraded
        snap["tenants"] = len(self._keyed.keys)
        return snap

    # ------------------------------------------------------------------ internals

    def _alloc_slot(self, key: Hashable) -> Optional[int]:
        return self._keyed.slot_for(key)

    def _sync_state(self, state: Any) -> Any:
        # multi-host serving rides the comm plane (codecs, coalesced transfers,
        # retry/degradation ladder) with its own site label so engine syncs are
        # attributable separately from bare sync_state_host callers
        if isinstance(self._metric, MetricCollection):
            return {
                name: sync_state_host(sub, self._metric._modules[name]._reductions, site="engine.compute")
                for name, sub in state.items()
            }
        return sync_state_host(state, self._metric._reductions, site="engine.compute")

    def _run(self) -> None:
        while True:
            with self._not_empty:
                while not self._queue and not self._closed:
                    self._not_empty.wait(0.1)
                if not self._queue and self._closed:
                    return
                batch = self._queue
                self._queue = []
                self._inflight = len(batch)
                self.telemetry.gauge_queue_depth(0)
                self._not_full.notify_all()
            self._worker_gate.wait()
            try:
                self._process(batch)
                with self._lock:
                    self._inflight = 0
                    self._idle.notify_all()
            except BaseException as exc:  # noqa: BLE001 — dispatcher death: degrade, don't lose work
                self._on_worker_death(exc, batch)
                return

    def _process(self, batch: List[_Request]) -> None:
        if self._fused:
            try:
                self._process_fused(batch)
                return
            except _FusedUnsupported:
                pass
            # A trace failure is ambiguous: the metric's update may be untraceable
            # (demote permanently), or ONE malformed request may have poisoned its
            # chunk's trace (reject that request, keep the fused path for everyone
            # else). The eager retry distinguishes them: it re-runs the same updates
            # outside the trace, so a malformed request fails ITS future there while
            # an untraceable-but-valid update succeeds for every request.
            remaining = [req for req in batch if not req.future.done()]
            self._process_eager(remaining)
            if remaining and all(req.future.exception() is None for req in remaining):
                self._demote_to_eager()
            return
        self._process_eager([req for req in batch if not req.future.done()])

    # ---------------------------------------------------- fused (bucketed) dispatch

    def _process_fused(self, batch: List[_Request]) -> None:
        with self._dispatch_lock:
            if self._keyed.ensure_capacity():
                self.telemetry.count("key_growths")
            for signature, reqs in self._signature_groups(batch):
                self._dispatch_group(signature, reqs)

    @staticmethod
    def _signature_groups(batch: List[_Request]) -> List[Tuple[Signature, List[_Request]]]:
        """Coalesce the drained batch into dispatch groups of one shape signature.

        Batch-wide grouping maximizes bucket occupancy but replays a tenant's
        requests signature-by-signature, which reorders them when ONE tenant mixes
        shapes in the same drain. Per-tenant submission order is part of the engine's
        sequential-semantics contract, so that (rare) case falls back to grouping by
        consecutive same-signature runs — order-preserving, slightly smaller
        micro-batches."""
        tenant_sig: Dict[Hashable, Signature] = {}
        mixed = False
        for req in batch:
            prev = tenant_sig.setdefault(req.key, req.signature)
            if prev != req.signature:
                mixed = True
                break
        groups: List[Tuple[Signature, List[_Request]]] = []
        if not mixed:
            by_sig: Dict[Signature, List[_Request]] = {}
            for req in batch:
                by_sig.setdefault(req.signature, []).append(req)
            groups.extend(by_sig.items())
        else:
            for req in batch:
                if groups and groups[-1][0] == req.signature:
                    groups[-1][1].append(req)
                else:
                    groups.append((req.signature, [req]))
        return groups

    def _dispatch_group(self, signature: Signature, reqs: List[_Request]) -> None:
        # expand oversized requests into row-chunks, then greedily pack chunks into
        # micro-batches of at most max_rows rows
        units: List[Tuple[_Request, Tuple[Any, ...], int, bool]] = []
        for req in reqs:
            chunks = split_rows(req.args, self._max_rows)
            for i, (chunk_args, rows) in enumerate(chunks):
                units.append((req, chunk_args, rows, i == len(chunks) - 1))

        pending: List[Tuple[_Request, Tuple[Any, ...], int, bool]] = []
        pending_rows = 0
        for unit in units:
            if pending and pending_rows + unit[2] > self._max_rows:
                self._dispatch_chunk(signature, pending, pending_rows)
                pending, pending_rows = [], 0
            pending.append(unit)
            pending_rows += unit[2]
        if pending:
            self._dispatch_chunk(signature, pending, pending_rows)

    def _dispatch_chunk(
        self,
        signature: Signature,
        units: List[Tuple[_Request, Tuple[Any, ...], int, bool]],
        total_rows: int,
    ) -> None:
        bucket = choose_bucket(total_rows, self._buckets)
        kernel = self._get_kernel(signature, bucket, self._keyed.capacity)
        columns, key_ids, mask = pad_micro_batch(
            [(req.slot, chunk_args, rows) for req, chunk_args, rows, _ in units], bucket
        )
        with _obs.engine_span("engine.dispatch", bucket=bucket, rows=total_rows):
            self._keyed.stacked = kernel(self._keyed.stacked, key_ids, mask, *columns)
            # commit before completing futures: surfaces device-side errors here and
            # makes the receipt mean "your rows are in the state", not "your rows are
            # enqueued"
            jax.block_until_ready(self._keyed.stacked)
        self.telemetry.observe_batch(total_rows, bucket)
        now = time.perf_counter()
        for req, _, rows, is_last in units:
            req.rows_done += rows
            if not is_last:
                continue
            self.telemetry.count("processed")
            self.telemetry.observe_latency(now - req.t_submit)
            req.future.set_result({"key": req.key, "rows": req.rows, "bucket": bucket})

    def _get_kernel(self, signature: Signature, bucket: int, capacity: int) -> Callable:
        cache_key = (signature, bucket, capacity)
        kernel = self._kernels.get(cache_key)
        if kernel is None:
            # kernel-cache miss == one fresh XLA compile: attribute it to the
            # request signature that caused it (obs retrace attribution)
            if _OBS.enabled:
                _obs.record_engine_compile(signature, bucket, capacity)
            kernel = self._build_kernel()
            self._kernels[cache_key] = kernel
        return kernel

    def _build_kernel(self) -> Callable:
        """One jitted micro-batch kernel: masked per-row scan over the stacked state.

        The scan body runs the metric's own ``update_state`` on the addressed tenant's
        slice and `where`-selects the pre-update state for masked (padding) rows, then
        scatters the slice back — sequential per-tenant semantics, one XLA dispatch for
        the whole micro-batch across all tenants. The input stack is donated: the
        engine owns it exclusively, so XLA can update the buffers in place.
        """
        metric = self._metric
        telemetry = self.telemetry

        def kernel(stacked: Any, key_ids: jax.Array, mask: jax.Array, *columns: jax.Array) -> Any:
            # executes at trace time only — counts actual recompiles, not calls
            telemetry.count("compiles")

            def step(carry: Any, xs: Tuple[Any, ...]) -> Tuple[Any, None]:
                kid, mk = xs[0], xs[1]
                rows = xs[2:]
                per_key = jax.tree.map(lambda s: s[kid], carry)
                new = metric.update_state(per_key, *rows)
                new = jax.tree.map(lambda n, o: jnp.where(mk, n, o), new, per_key)
                carry = jax.tree.map(lambda s, n: s.at[kid].set(n), carry, new)
                return carry, None

            carry, _ = lax.scan(step, stacked, (key_ids, mask, *columns))
            return carry

        jitted = jax.jit(kernel, donate_argnums=0)

        def guarded(stacked: Any, key_ids: jax.Array, mask: jax.Array, *columns: jax.Array) -> Any:
            try:
                return jitted(stacked, key_ids, mask, *columns)
            except Exception as exc:  # noqa: BLE001
                # Trace/compile failures here are either an untraceable metric update
                # (TracerBoolConversionError/ConcretizationTypeError as TypeError,
                # data-dependent masking as IndexError) or ONE malformed request
                # poisoning its chunk (shape errors as TypeError/ValueError). Both
                # funnel into _process's eager retry, which re-runs the same updates
                # outside the trace — a genuine bug is re-raised on its own request's
                # future, never masked, and the dispatcher never dies on a per-chunk
                # failure.
                raise _FusedUnsupported(repr(exc)) from exc

        return guarded

    def _demote_to_eager(self) -> None:
        """Permanent fused→eager fallback: migrate accumulated stacked state."""
        with self._dispatch_lock:
            old = self._keyed
            eager = EagerKeyedState(self._metric, window=self._window)
            for key in old.keys:
                eager.slot_for(key)
                eager.set_state(key, old.state_of(key))
            if old._ring is not None and eager._ring is not None:
                for cap, snap in old._ring:
                    seg: Dict[Hashable, Any] = {}
                    for key in old.keys:
                        slot = old._slots[key]
                        if slot < cap:
                            seg[key] = jax.tree.map(lambda x: x[slot], snap)
                    eager._ring.append(seg)
            self._keyed = eager
            self._fused = False
            self._kernels.clear()
        self.telemetry.count("fused_fallbacks")

    # ---------------------------------------------------- eager / degraded dispatch

    def _process_eager(self, batch: List[_Request]) -> None:
        for req in batch:
            self._apply_inline(req)

    def _apply_inline(self, req: _Request) -> None:
        """Synchronous per-request dispatch (eager mode, and the degraded path).

        Applies only the rows a fused chunk has not already committed, so a request
        caught mid-demotion is never double-counted.
        """
        try:
            args = req.args if req.rows_done == 0 else tuple(a[req.rows_done :] for a in req.args)
            with _obs.engine_span("engine.inline", rows=req.rows), self._dispatch_lock:
                if isinstance(self._keyed, EagerKeyedState):
                    self._keyed.update(req.key, *args)
                else:
                    state = self._keyed.state_of(req.key)
                    state = self._metric.update_state(state, *args)
                    self._keyed.set_state(req.key, state)
        except Exception as exc:  # noqa: BLE001 — fail THIS request, keep serving
            self.telemetry.count("failed")
            req.future.set_exception(exc)
            return
        self.telemetry.count("processed")
        if self._degraded or self._worker is None:
            # only true caller-thread dispatch counts: the healthy eager path also
            # lands here, and counting it would make a healthy engine look degraded
            self.telemetry.count("inline_dispatches")
        self.telemetry.observe_latency(time.perf_counter() - req.t_submit)
        req.future.set_result({"key": req.key, "rows": req.rows, "bucket": None})

    def _on_worker_death(self, exc: BaseException, batch: List[_Request]) -> None:
        """Dispatcher crashed: complete all accepted work inline, then degrade.

        ``_inflight`` stays equal to the unreplayed remainder throughout, so a
        concurrent ``flush()`` keeps blocking until the replay finishes — 'accepted
        implies committed after flush' holds across the degradation.
        """
        self._worker_error = exc
        self.telemetry.count("worker_deaths")
        with self._lock:
            self._degraded = True
            pending = [req for req in batch if not req.future.done()] + self._queue
            self._queue = []
            self._inflight = len(pending)
            self.telemetry.gauge_queue_depth(0)
            self._not_full.notify_all()
        try:
            for req in pending:
                self._apply_inline(req)
                with self._lock:
                    self._inflight -= 1
        finally:
            with self._lock:
                self._inflight = 0
                self._idle.notify_all()
