"""Shape-bucketed padding of incoming requests — keeps the jit cache bounded and hot.

A serving process receives requests of arbitrary row counts. Dispatching the jitted
updater on the raw shapes would compile once per distinct row count (the same failure
mode BootStrapper's poisson path hit before ``_chunk_spans``: ~250 ms per cache miss).
Instead, coalesced request rows are padded up to a small fixed set of bucket sizes
(powers of two by default) with a boolean row mask, so the engine's per-bucket kernels
compile once each and every subsequent micro-batch reuses a hot executable.

Padded rows are *carried but never applied*: the dispatch kernel (runtime.py) selects
the pre-update state for masked rows, so padding contributes exactly zero to every
tenant's state — no reliance on the metric having a neutral input value.
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utils.exceptions import MetricsTPUUserError

# Default micro-batch row buckets. Small buckets keep padding waste low for trickle
# traffic; the largest bounds one dispatch's work under burst load.
DEFAULT_BUCKETS: Tuple[int, ...] = (8, 16, 32, 64, 128, 256)

# (trailing shape, dtype name) per positional arg — the jit-cache-relevant part of a
# request's shape, i.e. everything except the bucketed leading (row) axis.
Signature = Tuple[Tuple[Tuple[int, ...], str], ...]


def normalize_buckets(buckets: Sequence[int]) -> Tuple[int, ...]:
    """Sorted, deduplicated, validated bucket sizes."""
    sizes = sorted({int(b) for b in buckets})
    if not sizes or sizes[0] < 1:
        raise MetricsTPUUserError(f"`buckets` must be positive integers, got {buckets!r}")
    return tuple(sizes)


def inspect_request(args: Sequence[Any]) -> Tuple[int, Signature]:
    """Row count and shape signature of one request's positional arrays.

    Every arg must share the leading (row) axis — that is the axis the engine
    coalesces, masks and buckets over.
    """
    if not args:
        raise MetricsTPUUserError("submit() needs at least one array argument")
    rows = None
    sig: List[Tuple[Tuple[int, ...], str]] = []
    for a in args:
        arr = a if isinstance(a, (jax.Array, np.ndarray)) else np.asarray(a)
        if arr.ndim < 1:
            raise MetricsTPUUserError(
                "submit() arguments must have a leading batch axis (got a 0-d array); "
                "wrap scalars as shape-(1,) arrays"
            )
        if rows is None:
            rows = int(arr.shape[0])
        elif int(arr.shape[0]) != rows:
            raise MetricsTPUUserError(
                f"submit() arguments disagree on the leading axis: {rows} vs {int(arr.shape[0])}"
            )
        # canonical dtype, not the submitted one: pad_micro_batch feeds the kernel
        # through jnp.asarray, which canonicalizes (int64 -> int32 with x64 off) — a
        # raw-numpy client and a jnp client submitting identical data must share one
        # kernel, not trace duplicate ladders per submitted dtype
        canon = jax.dtypes.canonicalize_dtype(arr.dtype)
        sig.append((tuple(int(s) for s in arr.shape[1:]), np.dtype(canon).name))
    return rows, tuple(sig)


def choose_bucket(rows: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits ``rows`` (deterministic); the largest if none does.

    Callers split loads larger than the top bucket into several micro-batches, so
    returning the cap here keeps the choice total.
    """
    for b in buckets:
        if rows <= b:
            return b
    return buckets[-1]


def pad_micro_batch(
    requests: Sequence[Tuple[int, Sequence[Any], int]],
    bucket: int,
) -> Tuple[List[jax.Array], jax.Array, jax.Array]:
    """Assemble one padded micro-batch from coalesced requests.

    ``requests`` is a sequence of ``(slot, args, rows)`` triples whose total rows fit
    ``bucket``. Rows are laid out back-to-back in submission order (the dispatch kernel
    scans them in this order, preserving per-tenant sequential semantics); the tail is
    zero-padded and masked out. Returns ``(columns, key_ids, mask)`` where each column
    has shape ``(bucket, 1, *trailing)`` — the per-row scan slice keeps a leading batch
    axis of 1 so metric ``update_state`` sees an ordinary (tiny) batch.

    Padding rows carry the first request's slot id: it is always a valid index into the
    stacked state (so the masked gather/scatter stays in bounds) and the mask guarantees
    it is never applied.
    """
    total = sum(r for _, _, r in requests)
    if total > bucket:
        raise MetricsTPUUserError(f"micro-batch of {total} rows exceeds bucket {bucket}")
    n_args = len(requests[0][1])
    key_ids = np.full(bucket, requests[0][0], dtype=np.int32)
    mask = np.zeros(bucket, dtype=bool)
    columns: List[np.ndarray] = []
    for j in range(n_args):
        ref = np.asarray(requests[0][1][j])
        col = np.zeros((bucket, 1) + ref.shape[1:], dtype=ref.dtype)
        off = 0
        for slot, args, rows in requests:
            col[off : off + rows, 0] = np.asarray(args[j])
            if j == 0:
                key_ids[off : off + rows] = slot
                mask[off : off + rows] = True
            off += rows
        columns.append(col)
    return [jnp.asarray(c) for c in columns], jnp.asarray(key_ids), jnp.asarray(mask)


def split_rows(args: Sequence[Any], max_rows: int) -> List[Tuple[Sequence[Any], int]]:
    """Split one oversized request into row-chunks of at most ``max_rows``.

    Engine semantics are per-row streaming updates (see runtime.py), so chunking a
    request along rows is exact for the supported metric class.
    """
    rows, _ = inspect_request(args)
    if rows <= max_rows:
        return [(tuple(args), rows)]
    out: List[Tuple[Sequence[Any], int]] = []
    for lo in range(0, rows, max_rows):
        hi = min(lo + max_rows, rows)
        out.append((tuple(a[lo:hi] for a in args), hi - lo))
    return out
