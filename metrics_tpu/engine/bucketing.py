"""Shape-bucketed padding of incoming requests — keeps the jit cache bounded and hot.

A serving process receives requests of arbitrary row counts. Dispatching the jitted
updater on the raw shapes would compile once per distinct row count (the same failure
mode BootStrapper's poisson path hit before ``_chunk_spans``: ~250 ms per cache miss).
Instead, coalesced request rows are padded up to a small fixed set of bucket sizes
(powers of two by default) with a boolean row mask, so the engine's per-bucket kernels
compile once each and every subsequent micro-batch reuses a hot executable.

Padded rows are *carried but never applied*: the dispatch kernel (runtime.py) selects
the pre-update state for masked rows, so padding contributes exactly zero to every
tenant's state — no reliance on the metric having a neutral input value.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Mapping, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utils.exceptions import MetricsTPUUserError

# Default micro-batch row buckets. Small buckets keep padding waste low for trickle
# traffic; the largest bounds one dispatch's work under burst load.
DEFAULT_BUCKETS: Tuple[int, ...] = (8, 16, 32, 64, 128, 256)

# (trailing shape, dtype name) per positional arg — the jit-cache-relevant part of a
# request's shape, i.e. everything except the bucketed leading (row) axis.
Signature = Tuple[Tuple[Tuple[int, ...], str], ...]


@dataclass(frozen=True)
class BucketConfig:
    """The engine's micro-batch ladder as an explicit config object.

    ``ladder`` is the set of row sizes the bucket kernels compile for. The
    default stays the log2 ladder (:data:`DEFAULT_BUCKETS`); a deployment with
    a recorded request-size trace can hand :func:`tune_buckets` output here
    instead (see ``benchmarks/experiments/tune_bucket_ladder.py``), trading
    generic coverage for measured-traffic padding efficiency at the same
    compile-cache bound.
    """

    ladder: Tuple[int, ...] = DEFAULT_BUCKETS

    def normalized(self) -> Tuple[int, ...]:
        return normalize_buckets(self.ladder)


def normalize_buckets(buckets: Union[Sequence[int], BucketConfig]) -> Tuple[int, ...]:
    """Sorted, deduplicated, validated bucket sizes (accepts a BucketConfig)."""
    if isinstance(buckets, BucketConfig):
        buckets = buckets.ladder
    sizes = sorted({int(b) for b in buckets})
    if not sizes or sizes[0] < 1:
        raise MetricsTPUUserError(f"`buckets` must be positive integers, got {buckets!r}")
    return tuple(sizes)


def tune_buckets(
    measured_occupancy: Union[Iterable[int], Mapping[int, float]],
    *,
    max_buckets: int = len(DEFAULT_BUCKETS),
    max_rows: int = DEFAULT_BUCKETS[-1],
) -> Tuple[int, ...]:
    """Pick a bucket ladder from measured occupancy instead of log2 guessing.

    ``measured_occupancy`` is a recorded request-size trace: an iterable of
    observed per-request row counts (what the engine's batch-occupancy
    telemetry measures — ``telemetry.snapshot()['rows']`` per request, or a
    replayed submit log), or a pre-aggregated ``{rows: weight}`` mapping.

    Minimizes total padded rows over the trace subject to at most
    ``max_buckets`` kernel compilations, by exact dynamic programming over the
    distinct observed sizes (bucket boundaries only ever pay off ON an
    observed size): ``cost(i..j) = Σ w_k · (s_j − s_k)`` for sizes ``s_i..s_j``
    assigned to bucket ``s_j``. Sizes above ``max_rows`` are clamped — the
    engine splits oversized requests into ``max_rows`` chunks anyway
    (:func:`split_rows`), so the ladder never needs a rung above the cap.
    Returns the ladder ready for ``BucketConfig(ladder=...)``; empty traces
    return :data:`DEFAULT_BUCKETS` unchanged.
    """
    if int(max_buckets) < 1:
        raise MetricsTPUUserError(f"`max_buckets` must be >= 1, got {max_buckets}")
    weights: Dict[int, float] = {}
    if isinstance(measured_occupancy, Mapping):
        items: Iterable[Tuple[int, float]] = measured_occupancy.items()
    else:
        items = ((int(r), 1.0) for r in measured_occupancy)
    for rows, w in items:
        rows = int(rows)
        if rows < 1 or w <= 0:
            continue
        rows = min(rows, int(max_rows))
        weights[rows] = weights.get(rows, 0.0) + float(w)
    if not weights:
        return DEFAULT_BUCKETS
    sizes = sorted(weights)
    # bound the DP: past ~512 distinct sizes, collapse to a WEIGHT-quantile
    # grid — grid points are spent where the traffic mass is (a dominant size
    # always lands on itself), not uniformly over the distinct-size range.
    # Each size keeps its weight on the grid point at or above it, so the
    # padding-cost model stays an upper bound of the true cost.
    if len(sizes) > 512:
        w_sorted = np.asarray([weights[s] for s in sizes], dtype=np.float64)
        cum = np.cumsum(w_sorted)
        picks = np.searchsorted(cum, np.linspace(0.0, cum[-1], 512), side="left")
        grid = sorted({int(sizes[min(int(p), len(sizes) - 1)]) for p in picks} | {sizes[-1]})
        collapsed: Dict[int, float] = {}
        gi = 0
        for s in sizes:
            while grid[gi] < s:
                gi += 1
            collapsed[grid[gi]] = collapsed.get(grid[gi], 0.0) + weights[s]
        weights = collapsed
        sizes = sorted(weights)
    m = len(sizes)
    k_max = min(int(max_buckets), m)
    w = np.asarray([weights[s] for s in sizes], dtype=np.float64)
    s = np.asarray(sizes, dtype=np.float64)
    # cost[i, j]: padded rows when sizes i..j all round up to bucket s_j
    cum_w = np.concatenate([[0.0], np.cumsum(w)])
    cum_ws = np.concatenate([[0.0], np.cumsum(w * s)])

    def seg_cost(i: int, j: int) -> float:
        return s[j] * (cum_w[j + 1] - cum_w[i]) - (cum_ws[j + 1] - cum_ws[i])

    inf = float("inf")
    dp = np.full((k_max + 1, m), inf)
    parent = np.full((k_max + 1, m), -1, dtype=np.int64)
    for j in range(m):
        dp[1, j] = seg_cost(0, j)
    for b in range(2, k_max + 1):
        for j in range(b - 1, m):
            for i in range(b - 2, j):
                c = dp[b - 1, i] + seg_cost(i + 1, j)
                if c < dp[b, j]:
                    dp[b, j] = c
                    parent[b, j] = i
    # the top bucket must cover the largest observed size; fewer buckets than
    # max_buckets win automatically when extra rungs stop paying
    best_b = min(range(1, k_max + 1), key=lambda b: dp[b, m - 1])
    ladder: List[int] = []
    b, j = best_b, m - 1
    while j >= 0 and b >= 1:
        ladder.append(sizes[j])
        j = int(parent[b, j])
        b -= 1
    return tuple(sorted(ladder))


def inspect_request(args: Sequence[Any]) -> Tuple[int, Signature]:
    """Row count and shape signature of one request's positional arrays.

    Every arg must share the leading (row) axis — that is the axis the engine
    coalesces, masks and buckets over.
    """
    if not args:
        raise MetricsTPUUserError("submit() needs at least one array argument")
    rows = None
    sig: List[Tuple[Tuple[int, ...], str]] = []
    for a in args:
        arr = a if isinstance(a, (jax.Array, np.ndarray)) else np.asarray(a)
        if arr.ndim < 1:
            raise MetricsTPUUserError(
                "submit() arguments must have a leading batch axis (got a 0-d array); "
                "wrap scalars as shape-(1,) arrays"
            )
        if rows is None:
            rows = int(arr.shape[0])
        elif int(arr.shape[0]) != rows:
            raise MetricsTPUUserError(
                f"submit() arguments disagree on the leading axis: {rows} vs {int(arr.shape[0])}"
            )
        # canonical dtype, not the submitted one: pad_micro_batch feeds the kernel
        # through jnp.asarray, which canonicalizes (int64 -> int32 with x64 off) — a
        # raw-numpy client and a jnp client submitting identical data must share one
        # kernel, not trace duplicate ladders per submitted dtype
        canon = jax.dtypes.canonicalize_dtype(arr.dtype)
        sig.append((tuple(int(s) for s in arr.shape[1:]), np.dtype(canon).name))
    return rows, tuple(sig)


def choose_bucket(rows: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits ``rows`` (deterministic); the largest if none does.

    Callers split loads larger than the top bucket into several micro-batches, so
    returning the cap here keeps the choice total.
    """
    for b in buckets:
        if rows <= b:
            return b
    return buckets[-1]


def pad_micro_batch(
    requests: Sequence[Tuple[int, Sequence[Any], int]],
    bucket: int,
) -> Tuple[List[jax.Array], jax.Array, jax.Array]:
    """Assemble one padded micro-batch from coalesced requests.

    ``requests`` is a sequence of ``(slot, args, rows)`` triples whose total rows fit
    ``bucket``. Rows are laid out back-to-back in submission order (the dispatch kernel
    scans them in this order, preserving per-tenant sequential semantics); the tail is
    zero-padded and masked out. Returns ``(columns, key_ids, mask)`` where each column
    has shape ``(bucket, 1, *trailing)`` — the per-row scan slice keeps a leading batch
    axis of 1 so metric ``update_state`` sees an ordinary (tiny) batch.

    Padding rows carry the first request's slot id: it is always a valid index into the
    stacked state (so the masked gather/scatter stays in bounds) and the mask guarantees
    it is never applied.
    """
    total = sum(r for _, _, r in requests)
    if total > bucket:
        raise MetricsTPUUserError(f"micro-batch of {total} rows exceeds bucket {bucket}")
    n_args = len(requests[0][1])
    key_ids = np.full(bucket, requests[0][0], dtype=np.int32)
    mask = np.zeros(bucket, dtype=bool)
    columns: List[np.ndarray] = []
    for j in range(n_args):
        ref = np.asarray(requests[0][1][j])
        col = np.zeros((bucket, 1) + ref.shape[1:], dtype=ref.dtype)
        off = 0
        for slot, args, rows in requests:
            col[off : off + rows, 0] = np.asarray(args[j])
            if j == 0:
                key_ids[off : off + rows] = slot
                mask[off : off + rows] = True
            off += rows
        columns.append(col)
    return [jnp.asarray(c) for c in columns], jnp.asarray(key_ids), jnp.asarray(mask)


def split_rows(args: Sequence[Any], max_rows: int) -> List[Tuple[Sequence[Any], int]]:
    """Split one oversized request into row-chunks of at most ``max_rows``.

    Engine semantics are per-row streaming updates (see runtime.py), so chunking a
    request along rows is exact for the supported metric class.
    """
    rows, _ = inspect_request(args)
    if rows <= max_rows:
        return [(tuple(args), rows)]
    out: List[Tuple[Sequence[Any], int]] = []
    for lo in range(0, rows, max_rows):
        hi = min(lo + max_rows, rows)
        out.append((tuple(a[lo:hi] for a in args), hi - lo))
    return out
