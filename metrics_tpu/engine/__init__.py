"""Streaming metric engine — async micro-batched, multi-tenant metric serving.

Turns any ``Metric`` / ``MetricCollection`` into a high-throughput service::

    from metrics_tpu.engine import StreamingEngine

    engine = StreamingEngine(BinaryAccuracy(), buckets=(8, 64, 256), max_queue=1024)
    fut = engine.submit(client_id, preds, target)   # non-blocking; Future receipt
    value = engine.compute(client_id)               # flush + per-tenant compute
    engine.close()

Layout: ``bucketing.py`` (shape-bucketed padding), ``runtime.py`` (bounded-queue
dispatcher + jitted bucket kernels + backpressure/degradation + the durable
state plane wiring — ``checkpoint=CheckpointConfig(...)`` adds periodic async
snapshots, a WAL of accepted work, and exactly-once restart recovery via
``metrics_tpu.ckpt``), ``stream.py`` (stacked multi-tenant keyed state +
sliding windows), ``telemetry.py`` (counters, occupancy, p50/p99 latency —
registry-backed: the series appear in ``metrics_tpu.obs.render_prometheus()``
under a per-engine label). Overload/abuse protection is the guard plane
(``guard=GuardConfig(...)``, :mod:`metrics_tpu.guard`): quotas, fair drain,
deadlines + shedding, circuit breakers, quarantine, watchdog, and
``engine.health()`` — see docs/source/robustness.md. Read scale-out and hot
failover are the replication plane (``replication=ReplConfig(...)``,
:mod:`metrics_tpu.repl`): WAL shipping off the write path, bit-identical
follower replay, bounded-staleness reads, epoch-fenced promotion — see
docs/source/replication.md. Million-tenant residency is the tier plane
(``tier=TierConfig(...)``, :mod:`metrics_tpu.tier`): HBM-hot / host-RAM-warm /
disk-cold state tiering with journaled residency records and bit-identical
readmission — see docs/source/tiering.md.
"""

from metrics_tpu.engine.bucketing import (
    DEFAULT_BUCKETS,
    BucketConfig,
    choose_bucket,
    inspect_request,
    pad_micro_batch,
    tune_buckets,
)
from metrics_tpu.engine.runtime import (
    CheckpointConfig,
    EngineBackpressure,
    EngineClosed,
    StreamingEngine,
)
from metrics_tpu.engine.stream import EagerKeyedState, KeyedState
from metrics_tpu.engine.telemetry import EngineTelemetry
from metrics_tpu.guard import (
    DeadlineExceeded,
    EngineQuarantined,
    GuardConfig,
    GuardRejected,
    QuotaExceeded,
    RequestShed,
    TenantQuarantined,
)
from metrics_tpu.repl import (
    NotPrimaryError,
    NotPromotableError,
    ReplConfig,
    ReplicaLag,
    StalenessExceeded,
)
from metrics_tpu.tier import TierConfig

__all__ = [
    "DEFAULT_BUCKETS",
    "BucketConfig",
    "CheckpointConfig",
    "DeadlineExceeded",
    "EagerKeyedState",
    "EngineBackpressure",
    "EngineClosed",
    "EngineQuarantined",
    "EngineTelemetry",
    "GuardConfig",
    "GuardRejected",
    "KeyedState",
    "NotPrimaryError",
    "NotPromotableError",
    "QuotaExceeded",
    "ReplConfig",
    "ReplicaLag",
    "RequestShed",
    "StalenessExceeded",
    "StreamingEngine",
    "TenantQuarantined",
    "TierConfig",
    "choose_bucket",
    "inspect_request",
    "pad_micro_batch",
    "tune_buckets",
]
