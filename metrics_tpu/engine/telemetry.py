"""Engine observability: counters, batch-occupancy histogram, submit→result latency.

Rebased onto the library-wide registry (:mod:`metrics_tpu.obs.registry`): every
engine series lives in the process-global ``REGISTRY`` under a per-engine
``engine=<id>`` label, so one Prometheus scrape (``obs.render_prometheus()``)
exposes every live engine alongside the rest of the stack's instrumentation.
Recording is unconditional — the engine's own telemetry does not ride the
``obs.enable()`` master switch (that switch gates the *automatic*
instrumentation hooks; a subsystem that records explicitly always records).

:meth:`EngineTelemetry.snapshot` keeps its original flat-dict shape (counters,
``queue_depth``, ``batch_occupancy_hist``, ``latency_s``,
``mean_batch_occupancy``) so existing dashboards and tests are unaffected.

Counter names are a closed set: :meth:`count` on a name that was never declared
raises instead of silently minting a new series (a typo'd counter that reads 0
forever is worse than a crash at the call site); extend the set explicitly with
:meth:`register_counter`.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, Optional

import numpy as np

from metrics_tpu.obs.jsonl import append_jsonl
from metrics_tpu.obs.registry import REGISTRY, Registry

# Batch-occupancy histogram edges: fraction of real (unmasked) rows per dispatched
# micro-batch. Low occupancy means the bucket ladder is too coarse for the traffic.
_OCCUPANCY_EDGES = (0.25, 0.5, 0.75, 1.0)

# submit→commit latency edges (seconds): 100µs → 10s decades, engine-shaped
_LATENCY_EDGES = (1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0)

_COUNTERS = (
    "submitted",          # requests accepted into the queue (or applied inline)
    "processed",          # requests whose state update committed
    "failed",             # requests completed with an exception
    "dropped",            # rejected by the drop policy at a full queue
    "timed_out",          # rejected by the timeout policy at a full queue
    "batches",            # micro-batches dispatched
    "rows",               # real rows committed
    "padded_rows",        # masked filler rows dispatched
    "compiles",           # kernel traces (== XLA compiles; counted at trace time)
    "fused_fallbacks",    # fused→eager demotions (untraceable metric update)
    "inline_dispatches",  # requests applied synchronously (degraded mode)
    "worker_deaths",      # dispatcher thread crashes survived
    "window_rotations",   # sliding-window segment rotations
    "key_growths",        # tenant-capacity doublings (each costs one recompile set)
    # durable state plane (zero unless the engine was built with checkpoint=)
    "checkpoints",          # snapshots committed (periodic + quiesce + close)
    "checkpoint_failures",  # snapshot/serialize/commit failures absorbed
    "wal_records",          # requests journaled ahead of their state commit
    "replayed",             # journaled requests re-applied during recovery
    "recoveries",           # restart-time restores from a valid snapshot
    # guard plane (zero unless the engine was built with guard=; see
    # metrics_tpu/guard/ and docs/source/robustness.md)
    "shed",                    # requests dropped by the overload controller
    "quota_rejections",        # submits refused by a tenant's token bucket
    "deadline_expired",        # requests whose deadline lapsed before dispatch
    "quarantines",             # tenants placed under failure probation
    "quarantine_rejections",   # submits failed fast from quarantined tenants
    "compile_rejections",      # novel-signature chunks routed eager by the compile breaker
    "ckpt_suspended",          # snapshot attempts skipped while the ckpt breaker is open
    "sync_pinned",             # sync=True computes served local state (comm breaker open)
    "worker_hangs",            # dispatchers declared hung by the watchdog
    "watchdog_restarts",       # fresh dispatchers started after a hang/death takeover
    # zombie surfacing is guard-independent: close() counts a worker that
    # outlived its join timeout whether or not a guard plane is configured
    "zombie_workers",
    # replication plane (zero unless the engine was built with replication=;
    # see metrics_tpu/repl/ and docs/source/replication.md)
    "shipped_records",      # WAL records published over the repl transport (primary)
    "shipped_snapshots",    # snapshot frames published (bootstrap + re-ship)
    "ship_failures",        # transient transport send failures absorbed + retried
    "applied_records",      # shipped WAL records replayed into local state (follower)
    "snapshot_loads",       # follower bootstraps/re-bootstraps from a shipped snapshot
    "fenced_rejections",    # frames/sends rejected by epoch fencing (zombie primary)
    "ship_journal_lost",    # shipper parked: engine disabled its WAL (IO failure)
    "ship_history_holes",   # bootstrap parked: best valid snapshot + retained WAL can't form a chain
    "apply_failures",       # follower frames that raised during apply (absorbed)
    "stale_read_refusals",  # follower reads refused beyond max_staleness
    "promotions",           # follower→primary promotions served by this engine
    "demotions",            # primary→follower step-downs (lease loss / re-attach)
    "read_jit_fallbacks",   # compiled read path disabled (trace failure; eager from then on)
    # tier plane (zero unless the engine was built with tier=; see
    # metrics_tpu/tier/ and docs/source/tiering.md)
    "tier_promotions",      # readmissions into the device slab (warm/cold -> hot)
    "tier_demotions",       # demotions out of the slab (hot -> warm mirror)
    "tier_spills",          # warm entries pushed to disk (warm -> cold)
    "tier_spill_failures",  # spill write failures absorbed (tenant stays warm)
    "tier_evictions",       # journaled tenant retirements (evict/export)
)

# distinguishes engines within one process; monotone so labels never collide
_ENGINE_IDS = itertools.count()

# observations between exported-quantile refreshes: the nearest-rank pass over
# the ring is O(window log window) — amortised to noise at this cadence
_QUANTILE_REFRESH = 64


class EngineTelemetry:
    """Registry-backed counters + histograms for one :class:`StreamingEngine`."""

    def __init__(
        self,
        latency_window: int = 2048,
        registry: Optional[Registry] = None,
        labels: Optional[Dict[str, str]] = None,
    ) -> None:
        reg = registry if registry is not None else REGISTRY
        self._registry = reg
        self.engine_id = str(next(_ENGINE_IDS))
        # extra labels ride on EVERY series of this engine — the shard plane
        # passes {"shard": "<i>"} so queue depth / occupancy / compiles are
        # filterable per shard in one Prometheus scrape
        self._label = {"engine": self.engine_id, **(labels or {})}

        self._events = reg.counter(
            "metrics_tpu_engine_events_total", "StreamingEngine request/dispatch lifecycle events."
        )
        self._depth = reg.gauge(
            "metrics_tpu_engine_queue_depth", "Requests queued but not yet drained by the dispatcher."
        )
        self._occupancy = reg.histogram(
            "metrics_tpu_engine_batch_occupancy",
            "Fraction of real (unmasked) rows per dispatched micro-batch.",
            buckets=_OCCUPANCY_EDGES,
        )
        self._latency = reg.histogram(
            "metrics_tpu_engine_latency_seconds",
            "submit()→commit latency, backpressure stalls included.",
            buckets=_LATENCY_EDGES,
        )
        self._resize_seconds = reg.counter(
            "metrics_tpu_engine_resize_seconds",
            "Cumulative wall time spent growing the stacked tenant slab "
            "(capacity doublings: one donated concat dispatch per dtype group).",
        )
        self._resize_key = self._resize_seconds.label_key(**self._label)
        self._resize_seconds.inc_key(self._resize_key, 0)

        # closed counter-name set, in declaration order (snapshot key order);
        # label identities are precomputed ONCE so the per-request hot path
        # (submit/process under the engine's >=10x acceptance gate) does a bare
        # dict-add under the counter lock — no per-call validation/sort/str
        self._allowed = list(_COUNTERS)
        self._event_keys = {
            name: self._events.label_key(event=name, **self._label) for name in self._allowed
        }
        for key in self._event_keys.values():
            self._events.inc_key(key, 0)
        self._depth_key = self._depth.label_key(**self._label)
        self._depth.set_key(self._depth_key, 0)
        self._occupancy_key = self._occupancy.label_key(**self._label)
        self._latency_key = self._latency.label_key(**self._label)

        # exact percentiles as scrapeable gauges: the bucketed histogram only
        # bounds quantiles to an edge pair, but the ring below holds exact
        # recent samples — export nearest-rank p50/p99 from it, refreshed every
        # _QUANTILE_REFRESH observations (the np.percentile pass is too costly
        # per-request) and on every snapshot()
        self._quantile = reg.gauge(
            "metrics_tpu_engine_latency_quantile_seconds",
            "Exact nearest-rank submit()→commit latency percentiles over the "
            "telemetry ring window (recent requests, not lifetime).",
        )
        self._quantile_keys = {
            q: self._quantile.label_key(quantile=q, **self._label) for q in ("0.5", "0.99")
        }

        # latency ring: fixed-size, overwritten oldest-first — exact-percentile
        # quality degrades gracefully under sustained load instead of growing
        # without bound (the registry histogram keeps only bucketed counts)
        self._ring_lock = threading.Lock()
        self._latencies = np.zeros(max(8, int(latency_window)), dtype=np.float64)
        self._lat_count = 0

    # ------------------------------------------------------------------ labeling

    def add_labels(self, **labels: str) -> None:
        """Stamp extra labels onto EVERY series of this engine, in place.

        The partition plane calls this at engine adoption
        (``partition="p<N>"``) so write-rate/backlog/latency attribution
        needs no client-side joins — the same contract the shard plane gets
        by passing ``telemetry_labels={"shard": ...}`` at construction, made
        retrofittable for engines built before their supervisor existed.

        Counter totals carry over to the relabeled series (cumulative-rate
        consumers like the autopilot see a rename, not a reset); histogram
        and quantile history restarts (bucket rows are not relabel-safe to
        merge). Keys already present with the same value are no-ops; a
        CONFLICTING value raises — two owners disagreeing about an engine's
        identity is a wiring bug, not a relabel.
        """
        new = {k: str(v) for k, v in labels.items() if self._label.get(k) != str(v)}
        for key in new:
            if key in self._label:
                raise ValueError(
                    f"telemetry label {key!r} is already {self._label[key]!r}; "
                    f"refusing to relabel to {new[key]!r} — one engine, one identity"
                )
        if not new:
            return
        old_label = dict(self._label)
        old_events = self._events.collect()
        carried = {
            name: float(old_events.get(key, 0.0)) for name, key in self._event_keys.items()
        }
        carried_resize = float(self._resize_seconds.value(**old_label))
        for inst in (self._events, self._depth, self._occupancy, self._latency,
                     self._resize_seconds, self._quantile):
            inst.drop_labels(**old_label)
        self._label = {**old_label, **new}
        self._resize_key = self._resize_seconds.label_key(**self._label)
        self._resize_seconds.inc_key(self._resize_key, carried_resize)
        self._event_keys = {
            name: self._events.label_key(event=name, **self._label) for name in self._allowed
        }
        for name, key in self._event_keys.items():
            self._events.inc_key(key, carried.get(name, 0))
        self._depth_key = self._depth.label_key(**self._label)
        self._depth.set_key(self._depth_key, 0)
        self._occupancy_key = self._occupancy.label_key(**self._label)
        self._latency_key = self._latency.label_key(**self._label)
        self._quantile_keys = {
            q: self._quantile.label_key(quantile=q, **self._label) for q in ("0.5", "0.99")
        }

    def label(self, name: str, default: str = "") -> str:
        """One stamped label's value (e.g. ``partition`` after adoption)."""
        return self._label.get(name, default)

    # ------------------------------------------------------------------ recording

    def register_counter(self, name: str) -> None:
        """Declare an extra counter name; only declared names may be counted."""
        if name not in self._allowed:
            self._allowed.append(name)
            key = self._events.label_key(event=name, **self._label)
            self._event_keys[name] = key
            self._events.inc_key(key, 0)

    def count(self, name: str, n: int = 1) -> None:
        key = self._event_keys.get(name)
        if key is None:
            raise KeyError(
                f"unknown telemetry counter {name!r}; declared: {sorted(self._allowed)}. "
                "Declare new names explicitly with register_counter() — a typo'd counter "
                "that silently reads 0 forever is a debugging trap."
            )
        self._events.inc_key(key, n)

    def gauge_queue_depth(self, depth: int) -> None:
        self._depth.set_key(self._depth_key, depth)

    def observe_batch(self, real_rows: int, bucket: int) -> None:
        frac = real_rows / bucket if bucket else 0.0
        # one lock acquisition for the batch's three counters: a concurrent
        # snapshot never sees rows committed without their batch/padding
        self._events.inc_many_keys(
            [
                (1, self._event_keys["batches"]),
                (real_rows, self._event_keys["rows"]),
                (bucket - real_rows, self._event_keys["padded_rows"]),
            ]
        )
        self._occupancy.observe_key(self._occupancy_key, frac)

    def observe_resize(self, seconds: float) -> None:
        """Add one slab-growth's wall time to ``metrics_tpu_engine_resize_seconds``."""
        self._resize_seconds.inc_key(self._resize_key, float(seconds))

    def observe_latency(self, seconds: float) -> None:
        self._latency.observe_key(self._latency_key, seconds)
        with self._ring_lock:
            self._latencies[self._lat_count % len(self._latencies)] = seconds
            self._lat_count += 1
            refresh = self._lat_count % _QUANTILE_REFRESH == 0
        if refresh:
            self._refresh_quantiles()

    def _refresh_quantiles(self) -> None:
        """Recompute the exported p50/p99 gauges from the latency ring."""
        with self._ring_lock:
            n = min(self._lat_count, len(self._latencies))
            lat = np.array(self._latencies[:n]) if n else None
        if lat is None:
            return
        p50, p99 = np.percentile(lat, [50, 99], method="nearest")
        self._quantile.set_key(self._quantile_keys["0.5"], float(p50))
        self._quantile.set_key(self._quantile_keys["0.99"], float(p99))

    # ------------------------------------------------------------------ reading

    def snapshot(self) -> Dict[str, Any]:
        """All counters + derived stats as one plain dict (original flat shape)."""
        # ONE collect() == one lock acquisition across every event series: the
        # counters are mutually consistent (submitted >= processed etc.), as the
        # pre-registry single-lock snapshot was
        events = self._events.collect()
        out: Dict[str, Any] = {
            name: int(events.get(self._event_keys[name], 0)) for name in self._allowed
        }
        out["queue_depth"] = int(self._depth.value(**self._label))
        out["resize_seconds"] = float(self._resize_seconds.value(**self._label))
        occ = self._occupancy.bucket_counts(**self._label)
        out["batch_occupancy_hist"] = {f"<={edge}": occ[edge] for edge in _OCCUPANCY_EDGES}
        with self._ring_lock:
            n = min(self._lat_count, len(self._latencies))
            lat = np.array(self._latencies[:n]) if n else None
            total = self._lat_count
        if lat is not None:
            # nearest-rank percentiles: p99 reaches max on small n (index
            # truncation made it unreachable below n=100 and degraded badly on a
            # partially-filled ring), and n=1 / wrapped-ring cases are exact
            p50, p99 = np.percentile(lat, [50, 99], method="nearest")
            # a snapshot is also a scrape point: publish fresh gauges so the
            # exported quantiles are never staler than the last snapshot
            self._quantile.set_key(self._quantile_keys["0.5"], float(p50))
            self._quantile.set_key(self._quantile_keys["0.99"], float(p99))
            out["latency_s"] = {
                "count": int(total),
                "p50": float(p50),
                "p99": float(p99),
                "max": float(lat.max()),
            }
        else:
            out["latency_s"] = {"count": 0, "p50": None, "p99": None, "max": None}
        out["mean_batch_occupancy"] = (
            out["rows"] / (out["rows"] + out["padded_rows"]) if out["batches"] else None
        )
        return out

    def emit(self, path: str, **extra: Any) -> Dict[str, Any]:
        """Append one snapshot as a JSONL record through the shared writer
        (:mod:`metrics_tpu.obs.jsonl` — same format/atomicity as ``tools/jsonl_log.py``)."""
        record: Dict[str, Any] = {"what": "engine_telemetry", **self.snapshot(), **extra}
        append_jsonl(path, record)
        return record

    # ------------------------------------------------------------------ lifecycle

    def retire(self) -> None:
        """Evict this engine's series from the process-global registry.

        The registry never evicts on its own, so a long-lived process creating
        many transient engines should call this once an engine (and any
        post-close snapshot reads — benchmarks read after ``close()``) is done
        with, or every future Prometheus scrape carries the dead engine's
        series. Recording after ``retire()`` is harmless: the series simply
        rematerialise.
        """
        for inst in (self._events, self._depth, self._occupancy, self._latency,
                     self._resize_seconds, self._quantile):
            inst.drop_labels(**self._label)
