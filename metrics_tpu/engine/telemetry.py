"""Engine observability: counters, batch-occupancy histogram, submit→result latency.

All recording is O(1) and lock-protected (submits land from many client threads, the
dispatcher records from its own); reads produce a plain dict so the snapshot can go
straight into logs, dashboards, or a ``tools/jsonl_log.py`` line.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict

import numpy as np


def _append_jsonl(path: str, record: Dict[str, Any]) -> None:
    """Same record format and atomicity contract as ``tools/jsonl_log.append_jsonl``
    (one O_APPEND line, failures noted on the record) — reimplemented here because
    ``tools/`` is repo tooling, not part of the installed package."""
    try:
        record.setdefault("utc", time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
        with open(path, "a") as fh:
            fh.write(json.dumps(record) + "\n")
    except Exception as exc:  # noqa: BLE001 — recording must never break serving
        record["log_error"] = repr(exc)

# Batch-occupancy histogram edges: fraction of real (unmasked) rows per dispatched
# micro-batch. Low occupancy means the bucket ladder is too coarse for the traffic.
_OCCUPANCY_EDGES = (0.25, 0.5, 0.75, 1.0)

_COUNTERS = (
    "submitted",          # requests accepted into the queue (or applied inline)
    "processed",          # requests whose state update committed
    "failed",             # requests completed with an exception
    "dropped",            # rejected by the drop policy at a full queue
    "timed_out",          # rejected by the timeout policy at a full queue
    "batches",            # micro-batches dispatched
    "rows",               # real rows committed
    "padded_rows",        # masked filler rows dispatched
    "compiles",           # kernel traces (== XLA compiles; counted at trace time)
    "fused_fallbacks",    # fused→eager demotions (untraceable metric update)
    "inline_dispatches",  # requests applied synchronously (degraded mode)
    "worker_deaths",      # dispatcher thread crashes survived
    "window_rotations",   # sliding-window segment rotations
    "key_growths",        # tenant-capacity doublings (each costs one recompile set)
)


class EngineTelemetry:
    """Thread-safe counters + histograms for one :class:`StreamingEngine`."""

    def __init__(self, latency_window: int = 2048) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {name: 0 for name in _COUNTERS}
        self._queue_depth = 0
        self._occupancy_hist = [0] * len(_OCCUPANCY_EDGES)
        # latency ring: fixed-size, overwritten oldest-first — percentile quality
        # degrades gracefully under sustained load instead of growing without bound
        self._latencies = np.zeros(max(8, int(latency_window)), dtype=np.float64)
        self._lat_count = 0

    # ------------------------------------------------------------------ recording

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge_queue_depth(self, depth: int) -> None:
        with self._lock:
            self._queue_depth = depth

    def observe_batch(self, real_rows: int, bucket: int) -> None:
        frac = real_rows / bucket if bucket else 0.0
        with self._lock:
            self._counters["batches"] += 1
            self._counters["rows"] += real_rows
            self._counters["padded_rows"] += bucket - real_rows
            for i, edge in enumerate(_OCCUPANCY_EDGES):
                if frac <= edge:
                    self._occupancy_hist[i] += 1
                    break

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self._latencies[self._lat_count % len(self._latencies)] = seconds
            self._lat_count += 1

    # ------------------------------------------------------------------ reading

    def snapshot(self) -> Dict[str, Any]:
        """All counters + derived stats as one plain dict."""
        with self._lock:
            out: Dict[str, Any] = dict(self._counters)
            out["queue_depth"] = self._queue_depth
            out["batch_occupancy_hist"] = {
                f"<={edge}": self._occupancy_hist[i] for i, edge in enumerate(_OCCUPANCY_EDGES)
            }
            n = min(self._lat_count, len(self._latencies))
            lat = np.sort(self._latencies[:n]) if n else None
        if lat is not None and n:
            out["latency_s"] = {
                "count": int(self._lat_count),
                "p50": float(lat[int(0.50 * (n - 1))]),
                "p99": float(lat[int(0.99 * (n - 1))]),
                "max": float(lat[-1]),
            }
        else:
            out["latency_s"] = {"count": 0, "p50": None, "p99": None, "max": None}
        batches = out["batches"]
        out["mean_batch_occupancy"] = (
            out["rows"] / (out["rows"] + out["padded_rows"]) if batches else None
        )
        return out

    def emit(self, path: str, **extra: Any) -> Dict[str, Any]:
        """Append one snapshot as a JSONL record (``tools/jsonl_log.py`` format)."""
        record: Dict[str, Any] = {"what": "engine_telemetry", **self.snapshot(), **extra}
        _append_jsonl(path, record)
        return record
