"""Multi-tenant keyed state: one logical metric, N client keys, one device dispatch.

Two regimes, one interface (``slot_for`` / ``state_of`` / ``rotate`` / ``merged_state``):

- :class:`KeyedState` — the fused regime. Every tenant's state pytree is stacked along
  a leading key axis, so the dispatch kernel (runtime.py) updates all tenants in ONE
  XLA dispatch via masked dynamic gather/scatter. Capacity grows by doubling (each
  growth changes the stacked shape, i.e. costs one recompile set — bounded log₂(K)).
- :class:`EagerKeyedState` — the host regime for metrics the fused kernel cannot trace
  (ragged "cat" list states, host-compute metrics): a plain dict of per-key state
  pytrees updated eagerly. Same tenancy and windowing semantics, more dispatches.

Sliding windows ride on the pure ``merge_states`` API: ``rotate()`` snapshots the
current segment into a ring (maxlen = window - 1) and resets the live segment;
``merged_state(key)`` folds the surviving ring segments into the live one. Eviction is
the ring's maxlen — no timestamps, no per-row bookkeeping.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from functools import partial
from typing import Any, Deque, Dict, Hashable, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from metrics_tpu.utils.exceptions import MetricsTPUUserError


@partial(jax.jit, static_argnums=2, donate_argnums=0)
def _grow_group(leaves: Tuple[Any, ...], inits: Tuple[Any, ...], pad_rows: int) -> Tuple[Any, ...]:
    """Grow every leaf of one dtype group in ONE compiled dispatch.

    The old leaves are donated: XLA frees (or reuses) each source buffer as its
    concat completes, so a resize under load never holds two full copies of the
    whole slab — peak transient memory is one dtype group, not the tree. The
    init pads are broadcast inside the trace (free at the XLA level), not
    materialised on the host. jit's own cache bounds compiles: capacity doubles
    log₂(K) times and each (shapes, pad_rows) pair compiles once.
    """
    return tuple(
        jnp.concatenate([leaf, jnp.broadcast_to(init, (pad_rows,) + init.shape)], axis=0)
        for leaf, init in zip(leaves, inits)
    )


def _validate_window(window: Optional[int]) -> Optional[int]:
    if window is None:
        return None
    window = int(window)
    if window < 1:
        raise MetricsTPUUserError(f"`window` must be >= 1 segment, got {window}")
    return window


class KeyedState:
    """Stacked per-key state for the fused dispatch path."""

    def __init__(
        self,
        metric: Any,
        capacity: int = 8,
        window: Optional[int] = None,
        device: Optional[Any] = None,
    ) -> None:
        self._metric = metric
        self._init = metric.init_state()
        self._device = device
        # strong-typed init leaves, grouped by dtype ONCE: ensure_capacity's
        # donated per-dtype-group grow and _tiled both read these (weak-typed
        # scalar inits would make every post-grow dispatch a jit-cache miss)
        init_leaves, self._treedef = jax.tree_util.tree_flatten(self._init)
        self._init_leaves: List[Any] = []
        for leaf in init_leaves:
            arr = jnp.asarray(leaf)
            arr = lax.convert_element_type(arr, arr.dtype)
            if device is not None:
                arr = jax.device_put(arr, device)
            self._init_leaves.append(arr)
        self._dtype_groups: List[List[int]] = []
        by_dtype: Dict[Any, List[int]] = {}
        for idx, leaf in enumerate(self._init_leaves):
            by_dtype.setdefault(jnp.asarray(leaf).dtype, []).append(idx)
        self._dtype_groups = list(by_dtype.values())
        self.last_resize_s = 0.0  # wall time of the most recent capacity growth
        self.capacity = 1
        while self.capacity < max(1, int(capacity)):
            self.capacity *= 2
        self.stacked = self._tiled(self.capacity)
        self._slots: Dict[Hashable, int] = {}
        self._max_slot = -1  # highest installed id (ids can be gapped — see slot_for)
        # retired slot ids eligible for reuse by NEW tenants. A slot only lands
        # here via release_slot(), which the engine calls AFTER journaling a
        # retire/demote record — WAL replay addresses rows by id, so an
        # unjournaled reuse would alias an old tenant's accumulator row.
        self._free_slots: List[int] = []
        self._free_set: set = set()
        # allocation is no longer single-threaded once the tier plane exists:
        # submit threads allocate under the engine lock while the dispatcher
        # promotes readmitted tenants under the dispatch lock — two locks, one
        # watermark. This micro-lock covers only the id handout (ns-scale).
        self._alloc_lock = threading.Lock()
        self.rotations = 0  # total rotate() calls — aligns demoted ring rows on readmit
        self.window = _validate_window(window)
        # ring entries are (capacity_at_snapshot, stacked_snapshot): a key allocated
        # after a snapshot was taken simply has no contribution in that segment
        self._ring: Optional[Deque[Tuple[int, Any]]] = (
            deque(maxlen=self.window - 1) if self.window and self.window > 1 else None
        )

    # ------------------------------------------------------------------ slots

    def _tiled(self, k: int) -> Any:
        # strong-typed leaves: scalar init values come in weak-typed, while the
        # kernel's outputs are strong-typed — mixing the two makes the first
        # dispatch after every reset/rotate a fresh jit-cache miss (a silent
        # ~100ms XLA recompile per bucket). The leaves were strong-typed (and
        # committed to this shard's device, when one was given) at __init__.
        return jax.tree_util.tree_unflatten(
            self._treedef,
            [jnp.broadcast_to(arr, (k,) + arr.shape) for arr in self._init_leaves],
        )

    @property
    def keys(self) -> Tuple[Hashable, ...]:
        return tuple(self._slots)

    def slot_for(self, key: Hashable) -> int:
        """Slot index for ``key``, allocating the next FREE one on first sight.

        Callers serialize allocation (the engine holds its submit lock); the slot may
        temporarily exceed ``capacity`` until the dispatcher calls ``ensure_capacity``.
        Retired slots (``release_slot``) are reused first — their retirement was
        journaled, so replay reproduces retire-then-reuse in order. Fresh
        allocation is ``max(installed ids) + 1``, not ``len(slots)``: WAL/ship
        replay installs the PRIMARY'S slot ids, which can arrive gapped (chunk
        commit order is not slot assignment order) — a length-based allocator
        would eventually hand a new tenant an id inside such a gap's occupied
        tail, silently sharing one accumulator row between two tenants.
        """
        slot = self._slots.get(key)
        if slot is None:
            with self._alloc_lock:
                slot = self._slots.get(key)
                if slot is None:
                    if self._free_slots:
                        slot = self._free_slots.pop()
                        self._free_set.discard(slot)
                    else:
                        slot = self._max_slot + 1
                        self._max_slot = slot
                    self._slots[key] = slot
        return slot

    def install_slot(self, key: Hashable, slot: int) -> int:
        """Install an externally assigned (primary's) slot id for ``key`` —
        WAL/ship replay's ``setdefault``, kept here so the max-id watermark that
        :meth:`slot_for` allocates above stays in sync. Returns the effective id
        (the existing one if ``key`` was already installed)."""
        with self._alloc_lock:
            existing = self._slots.setdefault(key, int(slot))
            self._max_slot = max(self._max_slot, existing)
            if existing in self._free_set:
                # replay handed us an id the primary reused after a journaled
                # retire: pull it off the local free-list so slot_for can't
                # double-allocate the row
                self._free_set.discard(existing)
                self._free_slots.remove(existing)
        return existing

    def ensure_capacity(self, min_slots: Optional[int] = None) -> bool:
        """Grow the key axis (doubling) to fit every allocated slot. True if grown.

        The needed capacity is ``max id + 1``, not ``len(slots)`` — replay can
        install the primary's ids gapped (see :meth:`slot_for`); ``min_slots``
        raises the floor further for ids a replayed chunk is about to index
        before they are all installed. Runs on every fused dispatch batch, so
        the watermark is a cached integer, never a scan of the slot map.
        """
        need = max(
            self._max_slot + 1,
            int(min_slots) if min_slots is not None else 0,
        )
        if need <= self.capacity:
            return False
        new_cap = self.capacity
        while new_cap < need:
            new_cap *= 2
        # ONE donated-buffer concat dispatch per dtype group (not per leaf): the
        # leaves of a group go through a single compiled call that pads each
        # with broadcast init rows, so a resize under load costs one device
        # dispatch per dtype instead of re-materialising the slab leaf-by-leaf.
        t0 = time.perf_counter()
        pad_rows = new_cap - self.capacity
        leaves = jax.tree_util.tree_flatten(self.stacked)[0]
        out = list(leaves)
        for idxs in self._dtype_groups:
            grown = _grow_group(
                tuple(leaves[i] for i in idxs),
                tuple(self._init_leaves[i] for i in idxs),
                pad_rows,
            )
            for i, leaf in zip(idxs, grown):
                out[i] = leaf
        self.stacked = jax.tree_util.tree_unflatten(self._treedef, out)
        # block for an honest wall-time figure (metrics_tpu_engine_resize_seconds);
        # growth happens log₂(K) times per tenant population, so the sync is noise
        jax.block_until_ready(self.stacked)
        self.capacity = new_cap
        self.last_resize_s = time.perf_counter() - t0
        return True

    # ------------------------------------------------------------------ reads

    def state_of(self, key: Hashable) -> Any:
        """Per-key live-segment state pytree (a fresh init state for a key that was
        allocated but never dispatched into the stacked buffer)."""
        slot = self._slots[key]
        if slot >= self.capacity:
            return self._metric.init_state()
        return jax.tree.map(lambda x: x[slot], self.stacked)

    def set_state(self, key: Hashable, state: Any) -> None:
        """Scatter one key's state back into the stack (degraded inline path)."""
        self.ensure_capacity()
        slot = self._slots[key]
        self.stacked = jax.tree.map(lambda s, n: s.at[slot].set(n), self.stacked, state)

    def evict(self, key: Hashable) -> Optional[int]:
        """Drop a tenant's tenancy: forget its slot, scrub its live row to init.

        Returns the freed slot id (or ``None`` if the key was unknown). The id
        is NOT immediately reusable — the caller must journal a retire/demote
        record first and then hand the id to :meth:`release_slot`, because
        WAL/ship replay installs ids positionally and an unjournaled reuse
        would share one accumulator row between two tenants' journals. Ring
        segments are NOT scrubbed here: ring reads are slot-addressed through
        ``_slots``, so a popped key's old rows are unreachable until the slot
        is reused — :meth:`release_slot` scrubs them before the id becomes
        reusable, so a NEW tenant landing on the id never inherits the old
        tenant's window contributions.
        """
        slot = self._slots.pop(key, None)
        if slot is None:
            return None
        if slot >= self.capacity:
            return slot
        self.stacked = jax.tree_util.tree_unflatten(
            self._treedef,
            [
                leaf.at[slot].set(init)
                for leaf, init in zip(
                    jax.tree_util.tree_flatten(self.stacked)[0], self._init_leaves
                )
            ],
        )
        return slot

    def release_slot(self, slot: Optional[int]) -> None:
        """Return a retired slot id to the free-list for reuse by NEW tenants.

        Callers gate this on a journaled retire record (runtime's ``b"T"`` /
        ``b"D"`` WAL kinds) so recovery replays retire-then-reuse in commit
        order and never aliases a dead tenant's row onto a live one. Window
        ring rows for the slot are scrubbed to init here — merged reads are
        slot-addressed, so without the scrub a new tenant reusing the id would
        inherit the retired tenant's window contributions. Callers hold the
        dispatch lock (ring segments are dispatch-locked state).
        """
        if slot is None:
            return
        slot = int(slot)
        with self._alloc_lock:
            if slot in self._free_set:
                return
            self._free_slots.append(slot)
            self._free_set.add(slot)
        if self._ring:
            for j, (cap, snap) in enumerate(self._ring):
                if slot >= cap:
                    continue
                snap = jax.tree_util.tree_unflatten(
                    self._treedef,
                    [
                        leaf.at[slot].set(init)
                        for leaf, init in zip(
                            jax.tree_util.tree_flatten(snap)[0], self._init_leaves
                        )
                    ],
                )
                self._ring[j] = (cap, snap)

    # ------------------------------------------------------------------ windowing

    def rotate(self) -> None:
        """Close the live segment: snapshot it into the ring, reset the live stack.

        With ``window=1`` there is no ring — rotation is a plain reset, i.e. only the
        live segment ever counts. The ring's maxlen evicts the oldest segment
        automatically once ``window`` segments exist.
        """
        if self.window is None:
            raise MetricsTPUUserError("rotate() requires the engine/state to be built with `window=`")
        if self._ring is not None:
            self._ring.append((self.capacity, self.stacked))
        self.stacked = self._tiled(self.capacity)
        self.rotations += 1

    def merged_state(self, key: Hashable) -> Any:
        """Window view: ring segments merged (oldest first) into the live segment."""
        state = self.state_of(key)
        if not self._ring:
            return state
        slot = self._slots[key]
        merged = None
        for cap, snap in self._ring:
            if slot >= cap:
                continue  # key didn't exist in this segment
            seg = jax.tree.map(lambda x: x[slot], snap)
            merged = seg if merged is None else self._metric.merge_states(merged, seg)
        return state if merged is None else self._metric.merge_states(merged, state)

    def reset(self) -> None:
        self.stacked = self._tiled(self.capacity)
        if self._ring is not None:
            self._ring.clear()


class EagerKeyedState:
    """Per-key host-side states for metrics the fused kernel cannot serve."""

    def __init__(self, metric: Any, window: Optional[int] = None) -> None:
        self._metric = metric
        self.last_resize_s = 0.0  # interface parity with KeyedState (never grows)
        self.rotations = 0  # interface parity — aligns demoted ring rows on readmit
        self._states: Dict[Hashable, Any] = {}
        self.window = _validate_window(window)
        self._ring: Optional[Deque[Dict[Hashable, Any]]] = (
            deque(maxlen=self.window - 1) if self.window and self.window > 1 else None
        )

    @property
    def keys(self) -> Tuple[Hashable, ...]:
        return tuple(self._states)

    def slot_for(self, key: Hashable) -> None:
        self._states.setdefault(key, self._metric.init_state())
        return None

    def ensure_capacity(self, min_slots: Optional[int] = None) -> bool:
        return False

    def state_of(self, key: Hashable) -> Any:
        return self._states[key]

    def set_state(self, key: Hashable, state: Any) -> None:
        self._states[key] = state

    def evict(self, key: Hashable) -> Optional[int]:
        """Drop a tenant everywhere. Unlike the stacked regime (slot-addressed,
        unreachable once the slot mapping is popped), eager ring segments are
        KEY-addressed — a re-registered key would resurrect its old window
        contributions, so the ring is scrubbed too."""
        self._states.pop(key, None)
        if self._ring is not None:
            for seg in self._ring:
                seg.pop(key, None)
        return None

    def release_slot(self, slot: Optional[int]) -> None:
        """Interface parity with KeyedState — eager states have no slots."""

    def update(self, key: Hashable, *args: Any) -> None:
        self._states[key] = self._metric.update_state(
            self._states.setdefault(key, self._metric.init_state()), *args
        )

    def rotate(self) -> None:
        if self.window is None:
            raise MetricsTPUUserError("rotate() requires the engine/state to be built with `window=`")
        if self._ring is not None:
            self._ring.append(self._states)
        self._states = {k: self._metric.init_state() for k in self._states}
        self.rotations += 1

    def merged_state(self, key: Hashable) -> Any:
        state = self.state_of(key)
        if not self._ring:
            return state
        merged = None
        for snap in self._ring:
            if key not in snap:
                continue
            merged = snap[key] if merged is None else self._metric.merge_states(merged, snap[key])
        return state if merged is None else self._metric.merge_states(merged, state)

    def reset(self) -> None:
        self._states = {k: self._metric.init_state() for k in self._states}
        if self._ring is not None:
            self._ring.clear()
