"""Core ``Metric`` runtime — stateful shell over a pure-functional, jittable core.

Reference parity: src/torchmetrics/metric.py (class Metric :43, add_state :162-230,
forward dual path :233-363, _sync_dist :365-395, sync/unsync/sync_context :428-521,
_wrap_update/_wrap_compute :397-426/:523-551, reset/clone :566-585, serialization
:587-596/:681-719, operator overloads :762-871, CompositionalMetric :878-978).

TPU-native redesign (SURVEY §7.1):

- State is a pytree of immutable ``jax.Array``s (fixed-shape states) and Python lists of
  arrays (ragged "cat" states). "Mutation" is attribute rebinding — so the reference's
  cache/restore gymnastics in ``forward`` reduce to holding references (free).
- Every metric exposes a **pure functional API** — ``init_state() / update_state(state,
  *args) / compute_from(state, axis_name=...) / merge_states(a, b)`` — that can be closed
  over by a user's ``pjit``/``shard_map`` training step, fusing metric accumulation into
  the compiled step graph. ``axis_name`` triggers XLA collectives (``psum`` et al.) for
  the sync instead of the reference's gather-then-reduce.
- The stateful shell (``update()/compute()/forward()/reset()``) keeps drop-in ergonomics
  for eval loops, with host-level multi-process sync via ``gather_all_tensors``.
"""

from __future__ import annotations

import functools
import math
import operator
import inspect
from abc import ABC, abstractmethod
from contextlib import contextmanager
from copy import deepcopy
from typing import Any, Callable, Dict, Generator, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.obs import instrument as _obs
from metrics_tpu.obs.registry import OBS as _OBS
from metrics_tpu.comm import plane as _comm_plane
from metrics_tpu.utils.data import (
    _flatten,
    _squeeze_if_scalar,
    apply_to_collection,
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
)
from metrics_tpu.utils.distributed import distributed_available, gather_all_tensors
from metrics_tpu.utils.exceptions import MetricsTPUUserError
from metrics_tpu.utils.prints import rank_zero_warn

_REDUCTION_FNS: Dict[str, Callable] = {
    "sum": dim_zero_sum,
    "mean": dim_zero_mean,
    "cat": dim_zero_cat,
    "min": dim_zero_min,
    "max": dim_zero_max,
}

# one fused dispatch for N state accumulations (see Metric._accumulate);
# compile cache keyed on the (structure, shapes, dtypes) of the operands
_tree_add = jax.jit(lambda olds, news: jax.tree_util.tree_map(jnp.add, olds, news))

_ZERO_STATE_CACHE: Dict[Any, Array] = {}
# Entry-count cap with FIFO eviction: each entry is ≤4096 elements, but programs
# constructing metrics over many distinct small shapes (varying num_classes /
# n_bins) would otherwise grow the key set without bound. A miss after eviction
# just falls back to jnp.zeros.
_ZERO_STATE_CACHE_MAX = 256


def zero_state(shape: Any = (), dtype: Any = None) -> Array:
    """A shared all-zeros array for ``add_state`` defaults.

    jax arrays are immutable, so every metric instance (and every state within
    one) can alias a single zeros buffer per (shape, dtype) instead of
    dispatching a fresh ``jnp.zeros`` per state per constructor (~55µs each
    eagerly — construction-dominated for small-state metrics built inside an
    eval loop). ``add_state`` already shares the default object with the live
    state, and compute-group detection compares states by value, never by
    identity, so cross-metric aliasing is safe.
    """
    if isinstance(shape, int):
        shape = (shape,)
    # same dtype semantics as jnp.zeros: the default is the x64-aware float,
    # and explicit requests are canonicalized (f64 -> f32 when x64 is off);
    # keying the cache on the canonical dtype keeps it correct if the x64
    # flag changes between constructions
    canon = jax.dtypes.canonicalize_dtype(float if dtype is None else dtype)
    # key on the active default device too: a zeros buffer cached under one
    # device must not serve a metric constructed under jax.default_device(...)
    # pointing elsewhere (.device would misreport until the first update)
    key = (tuple(shape), np.dtype(canon).name, str(jax.config.jax_default_device))
    if math.prod(key[0]) > 4096:
        # don't pin large buffers (e.g. binned-curve confmats at high
        # threshold/class counts) in the process-lifetime cache — the dispatch
        # saving is negligible against their allocation cost anyway
        return jnp.zeros(key[0], key[1])
    out = _ZERO_STATE_CACHE.get(key)
    if out is None:
        if len(_ZERO_STATE_CACHE) >= _ZERO_STATE_CACHE_MAX:
            try:  # tolerate a concurrent evictor winning the race for the same key
                _ZERO_STATE_CACHE.pop(next(iter(_ZERO_STATE_CACHE)), None)
            except (StopIteration, RuntimeError):
                pass
        out = _ZERO_STATE_CACHE.setdefault(key, jnp.zeros(key[0], key[1]))
    return out

StateValue = Union[Array, List[Array]]


def _cached_jitted_updater(obj: Any, donate: bool) -> Callable:
    """Shared body of ``{Metric,MetricCollection}.jitted_update_state``: one compiled
    updater per (instance, donate flag), cached under ``_jitted_update_state`` — the
    key both classes' ``__getstate__`` drops, since executables neither pickle nor
    deepcopy."""
    cache = obj.__dict__.setdefault("_jitted_update_state", {})
    fn = cache.get(donate)
    if fn is None:
        fn = jax.jit(obj.update_state, donate_argnums=0) if donate else jax.jit(obj.update_state)
        # retrace attribution (obs.instrument): the cached callable derives each
        # call's abstract-shape signature and records fresh ones — i.e. compiles
        # — against that signature; one attribute test per call when obs is off
        fn = _obs.wrap_jitted_updater(fn, obj)
        cache[donate] = fn
    return fn


def _raise_on_unconsumed(state_dict: Dict[str, Any], prefix: str, consumed: set) -> None:
    """Strict-mode guard shared by every ``load_state_dict`` implementation: any key
    under ``prefix`` that no (nested) metric consumed is unexpected — a silent skip
    would hide stale, misspelled, or misrouted checkpoint entries."""
    unexpected = sorted(k for k in state_dict if k.startswith(prefix) and k not in consumed)
    if unexpected:
        shown = ", ".join(unexpected[:8]) + (" ..." if len(unexpected) > 8 else "")
        raise KeyError(f"Unexpected key(s) in state_dict under prefix {prefix!r}: {shown}")

# kwargs consumed by Metric.__init__ (reference metric.py:82-144 + TPU axis_name
# extension) — wrappers that split base kwargs from passthrough kwargs key off this.
BASE_METRIC_KWARGS = frozenset(
    (
        "compute_on_cpu",
        "dist_sync_on_step",
        "process_group",
        "dist_sync_fn",
        "distributed_available_fn",
        "sync_on_compute",
        "axis_name",
    )
)


class Metric(ABC):
    """Base class for all metrics.

    Kwargs (reference metric.py:82-144): ``compute_on_cpu``, ``dist_sync_on_step``,
    ``process_group``, ``dist_sync_fn``, ``distributed_available_fn``,
    ``sync_on_compute``. TPU extension: ``axis_name`` — default mesh axis (or tuple of
    axes) that the functional ``compute_from`` syncs over when called inside a trace.
    """

    __jit_ignored_attributes__: Sequence[str] = ()  # kept for API parity

    is_differentiable: Optional[bool] = None
    higher_is_better: Optional[bool] = None
    full_state_update: Optional[bool] = False
    # True when compute() cannot run inside a trace (data-dependent shapes) — e.g.
    # exact-mode curve metrics; sync still works in-trace, compute happens on host.
    _host_compute: bool = False
    # Metric.plot() bounds/legend (reference utilities/plot.py:43 consumers); subclasses
    # with a known value range override these so the optimal value renders on the figure.
    plot_lower_bound: Optional[float] = None
    plot_upper_bound: Optional[float] = None
    plot_legend_name: Optional[str] = None

    def __init__(self, **kwargs: Any) -> None:
        self._device = None

        self.compute_on_cpu = kwargs.pop("compute_on_cpu", False)
        if not isinstance(self.compute_on_cpu, bool):
            raise ValueError(f"Expected keyword argument `compute_on_cpu` to be a `bool` but got {self.compute_on_cpu}")

        self.dist_sync_on_step = kwargs.pop("dist_sync_on_step", False)
        if not isinstance(self.dist_sync_on_step, bool):
            raise ValueError(f"Expected keyword argument `dist_sync_on_step` to be a `bool` but got {self.dist_sync_on_step}")

        self.process_group = kwargs.pop("process_group", None)

        self.dist_sync_fn = kwargs.pop("dist_sync_fn", None)
        if self.dist_sync_fn is not None and not callable(self.dist_sync_fn):
            raise ValueError(f"Expected keyword argument `dist_sync_fn` to be an callable function but got {self.dist_sync_fn}")

        self.distributed_available_fn = kwargs.pop("distributed_available_fn", None) or distributed_available

        self.sync_on_compute = kwargs.pop("sync_on_compute", True)
        if not isinstance(self.sync_on_compute, bool):
            raise ValueError(f"Expected keyword argument `sync_on_compute` to be a `bool` but got {self.sync_on_compute}")

        # TPU extension: default mesh axis for in-trace sync in the functional API.
        self.axis_name = kwargs.pop("axis_name", None)

        if kwargs:
            kwargs_ = [f"`{a}`" for a in sorted(kwargs)]
            raise ValueError(f"Unexpected keyword arguments: {', '.join(kwargs_)}")

        # state management
        self._defaults: Dict[str, StateValue] = {}
        self._persistent: Dict[str, bool] = {}
        self._reductions: Dict[str, Union[str, Callable, None]] = {}

        self._update_count = 0
        self._computed: Any = None
        self._to_sync = self.sync_on_compute
        self._should_unsync = True
        self._enable_grad = False

        self._cache: Optional[Dict[str, StateValue]] = None
        self._is_synced = False

        self._update_called = False
        self._forward_cache: Any = None
        self._batch_state: Optional[Dict[str, StateValue]] = None

        # wrap update/compute on the instance (reference metric.py:92-93)
        self.update: Callable = self._wrap_update(self.update)  # type: ignore[method-assign]
        self.compute: Callable = self._wrap_compute(self.compute)  # type: ignore[method-assign]

    # ------------------------------------------------------------------ state registry

    def add_state(
        self,
        name: str,
        default: StateValue,
        dist_reduce_fx: Optional[Union[str, Callable]] = None,
        persistent: bool = False,
    ) -> None:
        """Register a metric state (reference metric.py:162-230).

        ``default`` must be an array (fixed-shape state) or an empty list (ragged "cat"
        state). ``dist_reduce_fx`` ∈ {'sum','mean','cat','min','max', callable, None}.
        """
        if not isinstance(default, (jax.Array, np.ndarray, list)) or (isinstance(default, list) and default):
            raise ValueError("state variable must be a tensor or any empty list (where you can append tensors)")
        if isinstance(dist_reduce_fx, str):
            if dist_reduce_fx not in _REDUCTION_FNS:
                raise ValueError("`dist_reduce_fx` must be callable or one of ['mean', 'sum', 'cat', 'min', 'max', None]")
        elif not (callable(dist_reduce_fx) or dist_reduce_fx is None):
            raise ValueError("`dist_reduce_fx` must be callable or one of ['mean', 'sum', 'cat', 'min', 'max', None]")
        if name in ("_defaults", "_persistent", "_reductions", "update", "compute"):
            raise ValueError(f"The name `{name}` is reserved and cannot be used for a metric state")

        if not isinstance(default, list):
            default = jnp.asarray(default)

        setattr(self, name, [] if isinstance(default, list) else default)
        # jax arrays are immutable, so the registered default can be shared with
        # the live state outright — no deepcopy (which would dispatch a device
        # copy per state per constructor); list defaults are always empty here
        self._defaults[name] = [] if isinstance(default, list) else default
        self._persistent[name] = persistent
        self._reductions[name] = dist_reduce_fx

    # ------------------------------------------------------------------ update/compute (stateful shell)

    @abstractmethod
    def update(self, *args: Any, **kwargs: Any) -> None:
        """Override to update metric state from a batch."""

    @abstractmethod
    def compute(self) -> Any:
        """Override to compute the final value from accumulated state."""

    def _wrap_update(self, update: Callable) -> Callable:
        @functools.wraps(update)
        def wrapped_func(*args: Any, **kwargs: Any) -> None:
            self._computed = None
            self._update_count += 1
            self._update_called = True
            if self._is_synced:
                raise MetricsTPUUserError(
                    "The Metric has already been synced. HINT: call `unsync()` before modifying the state."
                )
            # named_scope: shows up in jax.profiler traces and XLA HLO metadata, the
            # tracing hook the reference lacks (SURVEY §5.1). The obs branch is
            # gated on ONE attribute test so the disabled hot path stays within
            # the <5% overhead budget (benchmarks/obs_overhead.py).
            if _OBS.enabled:
                with _obs.metric_op("update", self):
                    with jax.named_scope(f"{type(self).__name__}.update"):
                        update(*args, **kwargs)
            else:
                with jax.named_scope(f"{type(self).__name__}.update"):
                    update(*args, **kwargs)
            if self.compute_on_cpu:
                self._move_list_states_to_cpu()

        return wrapped_func

    def _move_list_states_to_cpu(self) -> None:
        """Move list states to host memory (reference metric.py:421-426)."""
        cpu = jax.devices("cpu")[0]
        for key in self._defaults:
            current = getattr(self, key)
            if isinstance(current, list):
                setattr(self, key, [jax.device_put(c, cpu) for c in current])

    def _accumulate(self, **increments: Any) -> None:
        """Add ``increments`` onto the same-named sum states in ONE dispatch.

        Only for states whose registered default is zero (the sum-state
        convention). ``state += x`` per state dispatches a separate tiny
        kernel each (~80µs/op eagerly on CPU); fusing all adds through one
        jitted tree-map halves the per-update overhead of multi-state
        metrics, and the first update after construction/reset skips the add
        entirely (states still alias their zero defaults, so assignment is
        exact). Under an outer jit the call inlines into the trace.
        """
        names = tuple(increments)
        olds = tuple(getattr(self, n) for n in names)
        if all(old is self._defaults[n] for n, old in zip(names, olds)):
            # untouched zero states (add_state/reset share the default object;
            # a loaded checkpoint replaces it, so this can't clobber one);
            # cast to the registered dtype so the state can't drift to e.g. an
            # int32 increment's dtype (the add path promotes the same way).
            # numpy increments with the right dtype stay numpy: the eager host
            # paths produce them, every consumer (compute jit, _tree_add,
            # state_dict, sync) accepts them, and skipping the device put here
            # saves ~55µs per state per update on the host backend
            for n, old in zip(names, olds):
                v = increments[n]
                if not (isinstance(v, (jax.Array, np.ndarray, np.generic)) and v.dtype == old.dtype):
                    v = jnp.asarray(v, old.dtype)
                setattr(self, n, v)
            return
        news = tuple(increments[n] for n in names)
        for n, v in zip(names, _tree_add(olds, news)):
            setattr(self, n, v)

    def _wrap_compute(self, compute: Callable) -> Callable:
        @functools.wraps(compute)
        def wrapped_func(*args: Any, **kwargs: Any) -> Any:
            if not self._update_called:
                rank_zero_warn(
                    f"The ``compute`` method of metric {self.__class__.__name__} was called before the ``update`` method"
                    " which may lead to errors, as metric states have not yet been updated.",
                    UserWarning,
                )
            if self._computed is not None:
                return self._computed

            # metric_op is a shared no-op when obs is disabled; compute is not
            # the per-batch hot path, so the single call is cheap enough here
            with _obs.metric_op("compute", self):
                with self.sync_context(
                    dist_sync_fn=self.dist_sync_fn,
                    process_group=self.process_group,
                    should_sync=self._to_sync,
                    should_unsync=self._should_unsync,
                ):
                    with jax.named_scope(f"{type(self).__name__}.compute"):
                        value = compute(*args, **kwargs)
                    self._computed = _squeeze_if_scalar(value)
            return self._computed

        return wrapped_func

    def _snapshot_state(self) -> Dict[str, StateValue]:
        """Shallow snapshot of all state attrs: immutable-array references plus
        shallow list copies (list states mutate in place during update)."""
        return {
            attr: (list(v) if isinstance(v, list) else v)
            for attr, v in ((a, getattr(self, a)) for a in self._defaults)
        }

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Accumulate global state AND return the metric value on this batch.

        Reference metric.py:233-252; the reduced path is the default because state is an
        immutable pytree here (snapshot = holding references).
        """
        if self._is_synced:
            raise MetricsTPUUserError("The Metric shouldn't be synced when performing ``forward``.")
        if self.full_state_update or self.full_state_update is None or self.dist_sync_on_step:
            return self._forward_full_state_update(*args, **kwargs)
        return self._forward_reduce_state_update(*args, **kwargs)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def _forward_full_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """2×-update path (reference metric.py:254-295)."""
        self.update(*args, **kwargs)
        _update_count = self._update_count
        self._to_sync = self.dist_sync_on_step
        cache = self._snapshot_state()
        self._should_unsync = False
        # reset to default values and compute batch-only value
        self.reset()
        self.update(*args, **kwargs)
        # stash the batch-only state for compute-group members
        # (MetricCollection's grouped forward), before the global state is
        # restored
        self._batch_state = self._snapshot_state()
        batch_val = self.compute()
        # restore context
        for attr, val in cache.items():
            setattr(self, attr, val)
        self._update_count = _update_count
        # with dist_sync_on_step the compute above synced WITHOUT unsyncing
        # (should_unsync=False); drop the sync marker or the next forward
        # raises "shouldn't be synced" (reference metric.py:286 does the same)
        self._is_synced = False
        self._cache = None
        self._should_unsync = True
        self._to_sync = self.sync_on_compute
        self._computed = None
        self._forward_cache = batch_val
        return batch_val

    def _forward_reduce_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """1×-update path + associative state merge (reference metric.py:297-363)."""
        global_state = self._snapshot_state()
        _update_count = self._update_count
        self.reset()

        self._to_sync = self.dist_sync_on_step
        self._should_unsync = False

        self.update(*args, **kwargs)
        # stash the batch-only state for compute-group members (see
        # _forward_full_state_update for the rationale)
        self._batch_state = self._snapshot_state()
        batch_val = self.compute()

        self._update_count = _update_count + 1
        self._reduce_states(global_state)

        # see _forward_full_state_update: clear the dist_sync_on_step sync
        # marker (reference metric.py:325)
        self._is_synced = False
        self._cache = None
        self._should_unsync = True
        self._to_sync = self.sync_on_compute
        self._computed = None
        self._forward_cache = batch_val
        return batch_val

    def _compute_batch_value(self, batch_state: Dict[str, StateValue]) -> Any:
        """This metric's per-batch forward value from an externally supplied
        batch-only state (a group leader's ``_batch_state``).

        Used by MetricCollection's grouped ``forward``: a compute-group member
        shares the leader's state evolution by group invariant, so its batch
        value is its OWN ``compute`` over the leader's batch state — no second
        update. The flag dance mirrors ``_forward_reduce_state_update`` (sync
        iff ``dist_sync_on_step``, like any forward batch value); this metric's
        stale global state is untouched (the group machinery re-aliases it from
        the leader at the next read).
        """
        saved = {attr: getattr(self, attr) for attr in self._defaults}
        saved_count = self._update_count
        for attr, val in batch_state.items():
            setattr(self, attr, val)
        self._update_count = 1
        self._update_called = True
        self._to_sync = self.dist_sync_on_step
        self._should_unsync = False
        self._computed = None
        batch_val = None
        try:
            batch_val = self.compute()
        finally:
            for attr, val in saved.items():
                setattr(self, attr, val)
            self._update_count = saved_count
            self._is_synced = False
            self._cache = None
            self._should_unsync = True
            self._to_sync = self.sync_on_compute
            self._computed = None
            self._forward_cache = batch_val
        return batch_val

    def _reduce_states(self, incoming_state: Dict[str, StateValue]) -> None:
        """Merge an incoming (global) state into the current (batch) state.

        Reference metric.py:336-363. sum: add; mean: running mean by update count;
        max/min: elementwise; cat: list concat; None: stack.
        """
        for attr in self._defaults:
            local_state = getattr(self, attr)
            global_state = incoming_state[attr]
            reduce_fn = self._reductions[attr]
            if reduce_fn == "sum":
                reduced = global_state + local_state
            elif reduce_fn == "mean":
                reduced = ((self._update_count - 1) * global_state + local_state) / self._update_count
            elif reduce_fn == "max":
                reduced = jnp.maximum(global_state, local_state)
            elif reduce_fn == "min":
                reduced = jnp.minimum(global_state, local_state)
            elif reduce_fn == "cat":
                reduced = global_state + local_state  # list concat
            elif reduce_fn is None and isinstance(global_state, (jax.Array, np.ndarray, np.generic)):
                reduced = jnp.stack([global_state, local_state])
            elif reduce_fn is None and isinstance(global_state, list):
                reduced = _flatten([global_state, local_state])
            else:
                fn = _REDUCTION_FNS.get(reduce_fn, reduce_fn) if isinstance(reduce_fn, str) else reduce_fn
                reduced = fn(jnp.stack([jnp.asarray(global_state), jnp.asarray(local_state)]))
            setattr(self, attr, reduced)

    # ------------------------------------------------------------------ distributed sync (host level)

    def _sync_dist(self, dist_sync_fn: Callable = gather_all_tensors, process_group: Optional[Any] = None) -> None:
        """Gather + reduce every registered state (reference metric.py:365-395)."""
        # numpy leaves (host-path increments kept native by _accumulate) must
        # become jax arrays HERE: the gather below is typed on jax.Array, and a
        # numpy scalar passing through un-gathered would silently miss the
        # cross-process reduction
        input_dict = {
            attr: jnp.asarray(v) if isinstance(v, (np.ndarray, np.generic)) else v
            for attr, v in ((attr, getattr(self, attr)) for attr in self._reductions)
        }

        if _OBS.enabled:
            # payload accounting BEFORE the gather: this is the byte volume the
            # all-gather moves per participant
            _obs.record_sync_bytes(
                "Metric._sync_dist", type(self).__name__, _obs.tree_nbytes(input_dict)
            )

        for attr, reduction_fn in self._reductions.items():
            # pre-concatenate metric states that are lists to reduce number of all-gathers
            if isinstance(input_dict[attr], list) and len(input_dict[attr]) >= 1:
                input_dict[attr] = [dim_zero_cat(input_dict[attr])]

        # the gather step rides the comm plane (spans + raw/wire accounting);
        # dist_sync_fn keeps the reference leaf protocol, and the default
        # gather_all_tensors runs on the configured comm transport underneath
        output_dict = _comm_plane.gather_metric_leaves(
            input_dict,
            dist_sync_fn,
            group=process_group or self.process_group,
        )

        for attr, reduction_fn in self._reductions.items():
            # normalise gathered list states before reduction: an empty cat
            # state stays an empty list
            if isinstance(output_dict[attr], list) and len(output_dict[attr]) == 0:
                setattr(self, attr, [])
                continue

            if isinstance(output_dict[attr][0], jax.Array):
                output_dict[attr] = jnp.stack(output_dict[attr])
            elif isinstance(output_dict[attr][0], list):
                output_dict[attr] = _flatten(output_dict[attr])

            fn = _REDUCTION_FNS.get(reduction_fn, reduction_fn) if isinstance(reduction_fn, str) else reduction_fn
            if not (callable(fn) or fn is None):
                raise TypeError("reduction_fn must be callable or None")
            reduced = fn(output_dict[attr]) if fn is not None else output_dict[attr]
            if isinstance(getattr(self, attr), list) and isinstance(reduced, jax.Array):
                reduced = [reduced]
            setattr(self, attr, reduced)

    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        distributed_available: Optional[Callable] = None,
    ) -> None:
        """Sync state across processes; caches the local state (reference metric.py:428-465)."""
        if self._is_synced and should_sync:
            raise MetricsTPUUserError("The Metric has already been synced.")

        if distributed_available is None and self.distributed_available_fn is not None:
            distributed_available = self.distributed_available_fn

        is_distributed = distributed_available() if callable(distributed_available) else None
        if not should_sync or not is_distributed:
            return

        if dist_sync_fn is None:
            dist_sync_fn = gather_all_tensors

        # cache prior to syncing
        self._cache = {attr: getattr(self, attr) for attr in self._defaults}
        self._cache = {k: list(v) if isinstance(v, list) else v for k, v in self._cache.items()}

        # sync (timed here rather than in _sync_dist so overriding subclasses —
        # CompositionalMetric's no-op, wrappers — stay covered)
        with _obs.metric_op("sync", self):
            self._sync_dist(dist_sync_fn, process_group=process_group)
        self._is_synced = True

    def unsync(self, should_unsync: bool = True) -> None:
        """Restore cached local state (reference metric.py:467-487)."""
        if not should_unsync:
            return
        if not self._is_synced:
            raise MetricsTPUUserError("The Metric has already been un-synced.")
        if self._cache is None:
            raise MetricsTPUUserError("The internal cache should exist to unsync the Metric.")

        for attr, val in self._cache.items():
            setattr(self, attr, val)
        self._is_synced = False
        self._cache = None

    @contextmanager
    def sync_context(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        should_unsync: bool = True,
        distributed_available: Optional[Callable] = None,
    ) -> Generator[None, None, None]:
        """Sync on enter, unsync on exit (reference metric.py:489-521)."""
        self.sync(
            dist_sync_fn=dist_sync_fn,
            process_group=process_group,
            should_sync=should_sync,
            distributed_available=distributed_available,
        )
        yield
        self.unsync(should_unsync=self._is_synced and should_unsync)

    # ------------------------------------------------------------------ pure functional API (TPU-first)

    def _raw_update(self) -> Callable:
        """The unwrapped user ``update``."""
        return type(self).update.__get__(self)

    def _raw_compute(self) -> Callable:
        return type(self).compute.__get__(self)

    def init_state(self) -> Dict[str, Any]:
        """Default state as a pytree (fixed states as arrays; ``_update_count`` included).

        Array leaves are fresh copies, never views of the stored defaults: the
        intended use is donating the state into a jitted step
        (``jax.jit(step, donate_argnums=...)``), and a donated buffer must not be
        the module's own default or a previously returned state.
        """
        state: Dict[str, Any] = {}
        for name, default in self._defaults.items():
            state[name] = [] if isinstance(default, list) else jnp.array(default, copy=True)
        state["_update_count"] = jnp.zeros((), dtype=jnp.int32)
        return state

    def _swap_in(self, state: Dict[str, Any]) -> Dict[str, Any]:
        snapshot: Dict[str, Any] = {name: getattr(self, name) for name in self._defaults}
        snapshot["_update_count"] = self._update_count
        for name in self._defaults:
            setattr(self, name, state[name])
        self._update_count = state.get("_update_count", 0)
        return snapshot

    def _swap_out(self, snapshot: Dict[str, Any]) -> Dict[str, Any]:
        state: Dict[str, Any] = {name: getattr(self, name) for name in self._defaults}
        state["_update_count"] = self._update_count
        for name in self._defaults:
            setattr(self, name, snapshot[name])
        self._update_count = snapshot["_update_count"]
        return state

    def update_state(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Pure: ``(state, batch) -> state``. Safe to call inside jit/shard_map/pjit."""
        snapshot = self._swap_in(state)
        try:
            with jax.named_scope(f"{type(self).__name__}.update_state"):
                self._raw_update()(*args, **kwargs)
            self._update_count = self._update_count + 1
        finally:
            new_state = self._swap_out(snapshot)
        return new_state

    def compute_from(self, state: Dict[str, Any], axis_name: Optional[Any] = None) -> Any:
        """Pure: final value from a state pytree; ``axis_name`` syncs via XLA collectives."""
        axis_name = axis_name if axis_name is not None else self.axis_name
        if axis_name is not None:
            state = self.sync_state(state, axis_name)
        snapshot = self._swap_in(state)
        try:
            with jax.named_scope(f"{type(self).__name__}.compute_from"):
                value = self._raw_compute()()
            return _squeeze_if_scalar(value)
        finally:
            self._swap_out(snapshot)

    def sync_state(self, state: Dict[str, Any], axis_name: Any) -> Dict[str, Any]:
        """In-trace sync: per-state XLA collective over ``axis_name`` mesh axes."""
        with jax.named_scope(f"{type(self).__name__}.sync_state"):
            return self._sync_state_impl(state, axis_name)

    def _sync_state_impl(self, state: Dict[str, Any], axis_name: Any) -> Dict[str, Any]:
        # one collective per state, emitted through the comm plane's traced path
        return _comm_plane.sync_pytree_in_trace(state, self._reductions, axis_name)

    def jitted_update_state(self, donate: bool = True) -> Callable:
        """The pure updater compiled with (optionally) donated state buffers.

        The serving-engine hook (``metrics_tpu/engine``): a runtime that owns its state
        pytree exclusively can donate it into the jitted update so XLA reuses the
        buffers in place — ``state = updater(state, preds, target)``. The caller must
        NOT touch a donated input state afterwards; compile cache is per instance and
        keyed on operand shapes/dtypes as usual.
        """
        return _cached_jitted_updater(self, donate)

    def merge_states(self, state_a: Dict[str, Any], state_b: Dict[str, Any]) -> Dict[str, Any]:
        """Associatively merge two state pytrees (pure analogue of ``_reduce_states``)."""
        merged: Dict[str, Any] = {}
        count_a = state_a.get("_update_count", 0)
        count_b = state_b.get("_update_count", 0)
        total = count_a + count_b
        for name, reduction in self._reductions.items():
            a, b = state_a[name], state_b[name]
            if reduction == "sum":
                merged[name] = a + b
            elif reduction == "mean":
                merged[name] = (count_a * a + count_b * b) / jnp.maximum(total, 1)
            elif reduction == "max":
                merged[name] = jnp.maximum(a, b)
            elif reduction == "min":
                merged[name] = jnp.minimum(a, b)
            elif reduction == "cat" or reduction is None:
                merged[name] = list(a) + list(b) if isinstance(a, list) else jnp.concatenate([a, b], axis=0)
            else:
                fn = reduction
                merged[name] = fn(jnp.stack([a, b]))
        merged["_update_count"] = total
        return merged

    # ------------------------------------------------------------------ reset / clone / device

    def reset(self) -> None:
        """Reset states to defaults (reference metric.py:566-580)."""
        self._update_count = 0
        self._update_called = False
        self._computed = None
        # drop the grouped-forward stash: it pins the last batch's whole state
        # (for cat-state metrics, the batch's preds/target arrays) otherwise
        self._batch_state = None

        for attr, default in self._defaults.items():
            if isinstance(default, list):
                setattr(self, attr, [])
            else:
                setattr(self, attr, jnp.asarray(default))

        # reset internal sync state
        self._cache = None
        self._is_synced = False

    def clone(self) -> "Metric":
        """Deep copy of the metric (reference metric.py:582-585)."""
        return deepcopy(self)

    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None) -> Any:
        """Plot a single computed value or a list of values as a time series.

        Reference surface: ``Metric.plot`` (metric.py:562-564) backed by
        ``utilities/plot.py:43``. With ``val=None`` the current ``compute()`` result is
        plotted. Requires matplotlib; returns ``(fig, ax)``.
        """
        from metrics_tpu.utils.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute()
        return plot_single_or_multi_val(
            val,
            ax=ax,
            higher_is_better=self.higher_is_better,
            lower_bound=self.plot_lower_bound,
            upper_bound=self.plot_upper_bound,
            legend_name=self.plot_legend_name,
            name=self.__class__.__name__,
        )

    def to_device(self, device: Any) -> "Metric":
        """Move all states (and defaults) to ``device`` (reference ``_apply``)."""
        self._device = device
        for attr in self._defaults:
            current = getattr(self, attr)
            if isinstance(current, list):
                setattr(self, attr, [jax.device_put(c, device) for c in current])
            else:
                setattr(self, attr, jax.device_put(current, device))
        self._defaults = {
            k: ([jax.device_put(vv, device) for vv in v] if isinstance(v, list) else jax.device_put(v, device))
            for k, v in self._defaults.items()
        }
        return self

    @property
    def device(self) -> Any:
        if self._device is not None:
            return self._device
        saw_host_state = False
        list_candidate = None
        # plain array states take priority over list entries: with
        # compute_on_cpu on an accelerator the list states are relocated to
        # the host while array states keep the true compute device
        for attr in self._defaults:
            val = getattr(self, attr)
            if isinstance(val, jax.Array):
                try:
                    return next(iter(val.devices()))
                except Exception:
                    return None
            if list_candidate is None and isinstance(val, list) and val and isinstance(val[0], jax.Array):
                list_candidate = val[0]
            if isinstance(val, (np.ndarray, np.generic)):
                saw_host_state = True
        if list_candidate is not None:
            try:
                return next(iter(list_candidate.devices()))
            except Exception:
                return None
        if saw_host_state:
            # numpy states (eager host-path increments kept native by
            # _accumulate) live in host memory — report the same device a
            # fresh jnp state would occupy on the cpu backend
            try:
                return jax.local_devices(backend="cpu")[0]
            except Exception:
                return None
        return None

    def set_dtype(self, dst_type: Any) -> "Metric":
        """Convert floating-point states to ``dst_type`` (reference metric.py:664-674)."""

        def _convert(x: Array) -> Array:
            return x.astype(dst_type) if jnp.issubdtype(x.dtype, jnp.floating) else x

        for attr in self._defaults:
            current = getattr(self, attr)
            if isinstance(current, list):
                setattr(self, attr, [_convert(c) for c in current])
            else:
                setattr(self, attr, _convert(current))
        self._defaults = {
            k: ([_convert(vv) for vv in v] if isinstance(v, list) else _convert(v)) for k, v in self._defaults.items()
        }
        return self

    # ------------------------------------------------------------------ persistence / serialization

    def _child_metrics(self):
        """Directly-held child metrics (wrapper bases, compositional operands),
        as ``(attr_path, metric)`` pairs. The reference gets nested-metric
        serialization for free from ``nn.Module`` child recursion
        (wrappers/minmax.py holds the base metric as a submodule); without it,
        a wrapped metric's accumulation would silently vanish through a
        checkpoint — found by the ``checkpoint_resume`` fuzz surface."""
        for name, val in self.__dict__.items():
            if isinstance(val, Metric):
                yield name, val
            elif isinstance(val, (list, tuple)):
                for i, v in enumerate(val):
                    if isinstance(v, Metric):
                        yield f"{name}.{i}", v

    def persistent(self, mode: bool = False) -> None:
        """Set persistence of all states, including directly-held child
        metrics' (reference metric.py:676-679; its CompositionalMetric
        recurses the same way at :965-969 — we extend that to every nested
        metric so ``wrapper.persistent(True)`` is sufficient to checkpoint)."""
        for key in self._persistent:
            self._persistent[key] = mode
        for _name, child in self._child_metrics():
            child.persistent(mode)

    def _any_persistent(self) -> bool:
        """True if any state here OR in any nested child metric is persistent —
        wrappers gate their extra checkpoint payload (running extremes, RNG
        streams) on this. A one-level check would read False for a
        wrapper-typed base (which registers no states of its own) and
        silently drop the payload."""
        if any(self._persistent.values()):
            return True
        return any(child._any_persistent() for _name, child in self._child_metrics())

    def state_dict(self, destination: Optional[Dict] = None, prefix: str = "") -> Dict[str, Any]:
        """Persistent states as a flat dict of numpy arrays (orbax-friendly pytree).

        Reference metric.py:681-700 — only states registered ``persistent=True`` are
        included, matching ``nn.Module.state_dict`` semantics.
        """
        destination = {} if destination is None else destination
        for key in self._defaults:
            if not self._persistent[key]:
                continue
            current = getattr(self, key)
            if isinstance(current, list):
                # array entries become numpy leaves (orbax-friendly); host-side
                # entries (e.g. detection's nested RLE tuples) pass through as
                # the picklable python objects they already are
                destination[prefix + key] = [
                    np.asarray(c) if isinstance(c, (jax.Array, np.ndarray)) else c for c in current
                ]
            else:
                destination[prefix + key] = np.asarray(current)
        for name, child in self._child_metrics():
            child.state_dict(destination, prefix=f"{prefix}{name}.")
        return destination

    def load_state_dict(
        self,
        state_dict: Dict[str, Any],
        prefix: str = "",
        strict: bool = True,
        _consumed: Optional[set] = None,
    ) -> None:
        """Inverse of :meth:`state_dict` (reference metric.py:702-719).

        ``strict=True`` raises on BOTH missing persistent keys and unexpected keys
        under this instance's prefix (``nn.Module.load_state_dict`` semantics — a
        stale or misrouted checkpoint entry must not vanish silently). ``_consumed``
        is internal plumbing: nested metrics record which keys they restored, and only
        the outermost call (``_consumed is None``) owns the unexpected-key check.
        """
        owns_check = _consumed is None
        consumed: set = set() if owns_check else _consumed
        for key in self._defaults:
            name = prefix + key
            if name in state_dict:
                consumed.add(name)
                val = state_dict[name]
                if isinstance(val, list):
                    # restore entries verbatim: state_dict saved numpy leaves,
                    # and host-compute metrics (detection) depend on numpy
                    # semantics (a jnp conversion here broke their area-range
                    # compares via weak-int overflow); device metrics accept
                    # numpy entries transparently in jnp ops
                    setattr(self, key, list(val))
                else:
                    setattr(self, key, jnp.asarray(val))
            elif strict and self._persistent[key]:
                raise KeyError(f"Missing key {name} in state_dict")
        for name, child in self._child_metrics():
            child.load_state_dict(state_dict, prefix=f"{prefix}{name}.", strict=strict, _consumed=consumed)
        if owns_check and strict:
            _raise_on_unconsumed(state_dict, prefix, consumed)

    def save(self, path: str, *, policy: Any = None, meta: Optional[Dict[str, Any]] = None) -> None:
        """Persist this metric's FULL state to ``path`` — atomic, checksummed,
        lossless by default (see :mod:`metrics_tpu.ckpt`).

        Unlike :meth:`state_dict` (reference-parity: persistent states only),
        ``save`` captures every registered state plus update counts, so
        ``restore`` on a fresh instance reproduces ``compute()`` bit-identically.
        ``policy`` opts into the comm plane's lossy codecs (counts stay exact).
        """
        from metrics_tpu.ckpt import save as _ckpt_save

        _ckpt_save(self, path, policy=policy, meta=meta)

    def restore(self, path: str) -> Any:
        """Load a :meth:`save` snapshot into this instance.

        Strict: integrity (CRC) failures raise
        :class:`~metrics_tpu.ckpt.CorruptSnapshotError`, schema/shape/dtype
        mismatches raise :class:`~metrics_tpu.ckpt.CkptSchemaError`, and
        missing/stray keys raise through the strict ``load_state_dict``
        machinery — in every case this instance is left as it was.
        """
        from metrics_tpu.ckpt import restore as _ckpt_restore

        return _ckpt_restore(self, path)

    def __getstate__(self) -> Dict[str, Any]:
        """Drop instance-wrapped fns for pickling (reference metric.py:587-591).

        The jitted-updater cache is dropped too: compiled executables neither pickle
        nor deepcopy, and a clone rebuilds them lazily on first use. The obs
        instance label is dropped so a clone gets its own telemetry series instead
        of aliasing its source's.
        """
        drop = ("update", "compute", "_jitted_update_state", "_obs_instance_label")
        return {k: v for k, v in self.__dict__.items() if k not in drop}

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        self.update = self._wrap_update(type(self).update.__get__(self))
        self.compute = self._wrap_compute(type(self).compute.__get__(self))

    def __setattr__(self, name: str, value: Any) -> None:
        if name in ("higher_is_better", "is_differentiable", "full_state_update"):
            raise RuntimeError(f"Can't change const `{name}`.")
        super().__setattr__(name, value)

    # ------------------------------------------------------------------ misc protocol

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        """Filter kwargs so they match the (unwrapped) update signature (metric.py:721-741)."""
        _params = (inspect.Parameter.VAR_POSITIONAL, inspect.Parameter.VAR_KEYWORD)
        _sign_params = self._update_signature.parameters
        filtered_kwargs = {
            k: v for k, v in kwargs.items() if (k in _sign_params and _sign_params[k].kind not in _params)
        }
        exists_var_keyword = any(v.kind == inspect.Parameter.VAR_KEYWORD for v in _sign_params.values())
        if exists_var_keyword:
            filtered_kwargs = kwargs
        return filtered_kwargs

    @property
    def _update_signature(self) -> inspect.Signature:
        return inspect.signature(type(self).update)

    @property
    def metric_state(self) -> Dict[str, StateValue]:
        """Current value of all registered states."""
        return {attr: getattr(self, attr) for attr in self._defaults}

    @property
    def update_called(self) -> bool:
        return self._update_called

    @property
    def update_count(self) -> int:
        return self._update_count

    def __hash__(self) -> int:
        # id(self) keeps fresh instances distinct (reference metric.py:743-749):
        # with shared zero_state defaults, two un-updated metrics of the same
        # class alias identical state objects, so state ids alone collide
        hash_vals: List[Any] = [self.__class__.__name__, id(self)]
        for key in self._defaults:
            val = getattr(self, key)
            if isinstance(val, list):
                # the list object id distinguishes instances even when both are empty
                hash_vals.append(id(val))
                hash_vals.extend(id(v) for v in val)
            else:
                hash_vals.append(id(val))
        return hash(tuple(hash_vals))

    def __repr__(self) -> str:
        return f"{self.__class__.__name__}()"

    def type(self, dst_type: Any) -> "Metric":  # noqa: A003 - parity with reference no-op
        """No-op (reference metric.py:644-662: precision management is explicit)."""
        return self

    def float(self) -> "Metric":
        return self

    def double(self) -> "Metric":
        return self

    def half(self) -> "Metric":
        return self

    # ------------------------------------------------------------------ operator overloads → CompositionalMetric

    def __add__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.add, self, other)

    def __radd__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.add, other, self)

    def __sub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.sub, self, other)

    def __rsub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.sub, other, self)

    def __mul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.mul, self, other)

    def __rmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.mul, other, self)

    def __truediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.truediv, self, other)

    def __rtruediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.truediv, other, self)

    def __floordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.floordiv, self, other)

    def __rfloordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.floordiv, other, self)

    def __mod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.mod, self, other)

    def __rmod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.mod, other, self)

    def __pow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.pow, self, other)

    def __rpow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.pow, other, self)

    def __matmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.matmul, self, other)

    def __rmatmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.matmul, other, self)

    def __and__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.and_, self, other)

    def __rand__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.and_, other, self)

    def __or__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.or_, self, other)

    def __ror__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.or_, other, self)

    def __xor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.xor, self, other)

    def __rxor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.xor, other, self)

    def __lt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.lt, self, other)

    def __le__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.le, self, other)

    def __gt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.gt, self, other)

    def __ge__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.ge, self, other)

    def __eq__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(operator.eq, self, other)

    def __ne__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(operator.ne, self, other)

    def __abs__(self) -> "CompositionalMetric":
        return CompositionalMetric(operator.abs, self, None)

    def __neg__(self) -> "CompositionalMetric":
        return CompositionalMetric(_neg, self, None)

    def __pos__(self) -> "CompositionalMetric":
        return CompositionalMetric(operator.abs, self, None)

    def __inv__(self) -> "CompositionalMetric":
        return CompositionalMetric(operator.inv, self, None)

    __invert__ = __inv__

    def __getitem__(self, idx: Any) -> "CompositionalMetric":
        return CompositionalMetric(operator.itemgetter(idx), self, None)

    def __getnewargs__(self) -> tuple:
        return ()


def _neg(x: Array) -> Array:
    return -jnp.abs(x)


class CompositionalMetric(Metric):
    """Lazy composition of metrics via an elementwise operator.

    Reference: metric.py:878-978. ``update``/``compute``/``reset``/``persistent``
    recurse into child metrics; its own ``_sync_dist`` is a no-op (children sync
    themselves inside their own ``compute``).

    Built by the 30+ arithmetic overloads on :class:`Metric`:

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanMetric
        >>> m1, m2 = MeanMetric(), MeanMetric()
        >>> combo = m1 + 2 * m2
        >>> m1.update(jnp.array(1.0))
        >>> m2.update(jnp.array(3.0))
        >>> combo.compute()
        Array(7., dtype=float32)
    """

    def __init__(self, operator: Callable, metric_a: Union[Metric, float, Array, None], metric_b: Union[Metric, float, Array, None]) -> None:
        super().__init__()
        self.op = operator
        self.metric_a = jnp.asarray(metric_a) if isinstance(metric_a, (int, float, np.ndarray)) and metric_a is not None and not isinstance(metric_a, bool) else metric_a
        self.metric_b = jnp.asarray(metric_b) if isinstance(metric_b, (int, float, np.ndarray)) and metric_b is not None and not isinstance(metric_b, bool) else metric_b

    def _sync_dist(self, dist_sync_fn: Optional[Callable] = None, process_group: Optional[Any] = None) -> None:
        pass  # No syncing required: children sync themselves.

    def update(self, *args: Any, **kwargs: Any) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.update(*args, **self.metric_a._filter_kwargs(**kwargs))
        if isinstance(self.metric_b, Metric):
            self.metric_b.update(*args, **self.metric_b._filter_kwargs(**kwargs))

    def compute(self) -> Any:
        # operands may be Metric instances (compute now) or captured constants
        val_a = self.metric_a.compute() if isinstance(self.metric_a, Metric) else self.metric_a
        val_b = self.metric_b.compute() if isinstance(self.metric_b, Metric) else self.metric_b
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        val_a = (
            self.metric_a(*args, **self.metric_a._filter_kwargs(**kwargs)) if isinstance(self.metric_a, Metric) else self.metric_a
        )
        val_b = (
            self.metric_b(*args, **self.metric_b._filter_kwargs(**kwargs)) if isinstance(self.metric_b, Metric) else self.metric_b
        )
        if val_a is None:
            self._forward_cache = None
            return self._forward_cache
        if val_b is None:
            if isinstance(self.metric_b, Metric):
                self._forward_cache = None
                return self._forward_cache
            self._forward_cache = self.op(val_a)
            return self._forward_cache
        self._forward_cache = self.op(val_a, val_b)
        return self._forward_cache

    def reset(self) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.reset()
        if isinstance(self.metric_b, Metric):
            self.metric_b.reset()

    # persistent() needs no override: the base class's _child_metrics recursion
    # reaches metric_a/metric_b (reference metric.py:965-969 recursed manually)

    def __repr__(self) -> str:
        _op_metrics = f"(\n  {self.op.__name__ if hasattr(self.op, '__name__') else self.op}(\n    {self.metric_a!r},\n    {self.metric_b!r}\n  )\n)"
        return self.__class__.__name__ + _op_metrics

    def _wrap_compute(self, compute: Callable) -> Callable:
        return compute
