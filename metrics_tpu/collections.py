"""MetricCollection — dict-of-metrics with shared-state compute groups.

Reference parity: src/torchmetrics/collections.py (class :28, forward :167,
update :177-202, compute-group machinery :204-282, compute :284).

Compute groups (reference docs claim 2x-3x update-cost reduction,
docs/source/pages/overview.rst:318-327): metrics whose updates produce identical
states (e.g. MulticlassPrecision/Recall/F1 over the same stat-scores) are detected
after the first update by pairwise state comparison; thereafter only the group leader
updates and members alias its state. With immutable jax.Arrays, aliasing is rebinding
attributes to the same arrays — the deepcopy escape hatch in ``items()`` etc. keeps
the reference's copy-on-read semantics.
"""

from __future__ import annotations

from collections import OrderedDict
from copy import deepcopy
from typing import Any, Dict, Hashable, Iterable, Iterator, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric, _cached_jitted_updater, _raise_on_unconsumed
from metrics_tpu.obs import instrument as _obs
from metrics_tpu.utils.data import _flatten_dict
from metrics_tpu.utils.prints import rank_zero_warn


class MetricCollection:
    """Dict of metrics with a single update/forward/compute/reset (reference :28).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MetricCollection
        >>> from metrics_tpu.classification import BinaryAccuracy, BinaryF1Score
        >>> collection = MetricCollection([BinaryAccuracy(), BinaryF1Score()])
        >>> preds = jnp.array([0, 0, 1, 1, 0, 1])
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> collection.update(preds, target)
        >>> {k: float(v) for k, v in collection.compute().items()}  # doctest: +ELLIPSIS
        {'BinaryAccuracy': 0.666..., 'BinaryF1Score': 0.666...}
    """

    _modules: "OrderedDict[str, Metric]"

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
        compute_groups: Union[bool, List[List[str]]] = True,
    ) -> None:
        self._modules = OrderedDict()
        self.prefix = self._check_arg(prefix, "prefix")
        self.postfix = self._check_arg(postfix, "postfix")
        self._enable_compute_groups = compute_groups
        self._groups_checked: bool = False
        self._state_is_copy: bool = False
        self._groups: Dict[int, List[str]] = {}

        self.add_metrics(metrics, *additional_metrics)

    @staticmethod
    def _check_arg(arg: Optional[str], name: str) -> Optional[str]:
        if arg is None or isinstance(arg, str):
            return arg
        raise ValueError(f"Expected input `{name}` to be a string, but got {type(arg)}")

    # ------------------------------------------------------------------ construction

    def add_metrics(
        self, metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]], *additional_metrics: Metric
    ) -> None:
        """Add metrics (reference collections.py ``add_metrics``)."""
        if self._modules and getattr(self, "_groups_checked", False):
            # Adding to a live collection invalidates the group structure.
            # Break state aliasing FIRST: list ('cat') states are shared by
            # object between leader and members, and once the rebuilt groups
            # split a former group both ex-members would append into the one
            # shared list, double-counting every subsequent batch.
            self._compute_groups_create_state_ref(copy=True)
        if isinstance(metrics, Metric):
            metrics = [metrics]
        if isinstance(metrics, Sequence) and not isinstance(metrics, dict):
            metrics = list(metrics)
            remain: list = []
            for m in additional_metrics:
                (metrics if isinstance(m, Metric) else remain).append(m)
            if remain:
                # reference collections.py:331-332: warn and ignore non-Metric extras
                rank_zero_warn(
                    f"You have passed extra arguments {remain} which are not `Metric` so they will be ignored.",
                    UserWarning,
                )
        elif additional_metrics:
            raise ValueError(
                f"You have passed extra arguments {additional_metrics} which are not compatible with first passed"
                " dictionary."
            )

        if isinstance(metrics, dict):
            for name in sorted(metrics.keys()):
                metric = metrics[name]
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Value {metric} belonging to key {name} is not an instance of"
                        " `metrics_tpu.Metric` or `metrics_tpu.MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    self._modules[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self._modules[f"{name}_{k}"] = v
        elif isinstance(metrics, Sequence):
            for metric in metrics:
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Input {metric} to `MetricCollection` is not a instance of"
                        " `metrics_tpu.Metric` or `metrics_tpu.MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    name = metric.__class__.__name__
                    if name in self._modules:
                        raise ValueError(f"Encountered two metrics both named {name}")
                    self._modules[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self._modules[k] = v
        else:
            raise ValueError("Unknown input to MetricCollection.")

        self._groups_checked = False
        if self._enable_compute_groups:
            self._init_compute_groups()
        else:
            self._groups = {i: [name] for i, name in enumerate(self._modules)}

    def _init_compute_groups(self) -> None:
        """Initialise compute groups (reference collections.py:~150)."""
        if isinstance(self._enable_compute_groups, list):
            self._groups = {i: v for i, v in enumerate(self._enable_compute_groups)}
            for v in self._groups.values():
                for metric in v:
                    if metric not in self._modules:
                        raise ValueError(
                            f"Input {metric} in `compute_groups` argument does not match a metric in the collection."
                        )
            self._groups_checked = True
        else:
            # Structural fast path (SURVEY §7.2 step 4): metrics sharing the
            # same update code, the same non-state config, and the same state
            # spec provably evolve identical states (update is a pure function
            # of config, inputs and prior state), so they are seeded into one
            # group here and the ported runtime value comparison
            # (_merge_compute_groups) only has to arbitrate the remaining
            # leaders — e.g. metrics of different classes whose states happen
            # to coincide in value, which the reference also merges. Seeding is
            # strictly a subset of what the runtime comparison would merge, so
            # group membership is identical to the reference's; only the
            # number of first-update allclose dispatches shrinks.
            if any(m._update_count for m in self._modules.values()):
                # add_metrics after real updates: a virgin metric can be
                # structurally identical to one that already carries history,
                # and seeding them together would alias that history onto the
                # newcomer. Let the runtime value merge arbitrate everything,
                # exactly as the reference does.
                self._groups = {i: [name] for i, name in enumerate(self._modules)}
                return
            groups: List[List[str]] = []
            for name in self._modules:
                m = self._modules[name]
                for g in groups:
                    if self._structurally_identical(self._modules[g[0]], m):
                        g.append(name)
                        break
                else:
                    groups.append([name])
            self._groups = dict(enumerate(groups))

    # Class-level names that provably cannot influence ``update``'s state
    # evolution: readout (compute/plot), constructors (config differences they
    # create surface as instance attrs, compared below), and display metadata.
    _CLASS_ATTR_ALLOW = frozenset({
        "compute", "plot", "__init__", "__doc__", "__module__", "__qualname__",
        "__firstlineno__", "__static_attributes__", "__annotations__",
        "__abstractmethods__", "_abc_impl", "__parameters__", "__orig_bases__",
        "is_differentiable", "higher_is_better", "full_state_update",
        "plot_lower_bound", "plot_upper_bound", "plot_legend_name",
    })
    # Instance attrs owned by the Metric runtime, not by metric config.
    _INSTANCE_ATTR_SKIP = frozenset({
        "_device", "_defaults", "_persistent", "_reductions", "_update_count",
        "_computed", "_to_sync", "_should_unsync", "_enable_grad", "_cache",
        "_is_synced", "_update_called", "_forward_cache", "_batch_state",
        "update", "compute",
    })

    @classmethod
    def _update_compatible_classes(cls, c1: type, c2: type) -> bool:
        """Every class-level name below ``Metric`` that could feed ``update``
        (helpers, properties, constants — e.g. the ``BLEUScore._tokenizer``
        property that ``SacreBLEUScore`` overrides) must resolve to the SAME
        object on both classes; readout/metadata names are exempt. Equal-but-
        distinct objects fail — a false negative only costs a runtime
        comparison."""
        if c1 is c2:
            return True
        names: set = set()
        for klass in (*c1.__mro__, *c2.__mro__):
            if klass is Metric:
                continue
            if issubclass(Metric, klass):  # ABC/object/Generic bases above Metric
                continue
            names.update(vars(klass))
        sentinel = object()
        return all(
            getattr(c1, n, sentinel) is getattr(c2, n, sentinel)
            for n in names
            if n not in cls._CLASS_ATTR_ALLOW
        )

    @classmethod
    def _structurally_identical(cls, m1: Metric, m2: Metric) -> bool:
        """True only when ``m1`` and ``m2`` provably produce equal states.

        Criteria: identical ``update`` function (class-level, not the
        per-instance forward wrapper), update-compatible classes (every
        non-readout class attribute the same object — catches inherited
        ``update`` calling an overridden helper), non-empty identical state
        specs (names, list-vs-array kind, default shapes/dtypes/values, reduce
        fx) and equal config attributes INCLUDING ``_``-prefixed ones (only
        runtime machinery is skipped). Callable config that is not the same
        object is conservatively treated as different; anything unrecognisable
        keeps the metrics apart — a false negative only costs a runtime
        comparison.
        """
        if type(m1).update is not type(m2).update:
            return False
        if not cls._update_compatible_classes(type(m1), type(m2)):
            return False
        if len(m1._defaults) == 0 or m1._defaults.keys() != m2._defaults.keys():
            return False
        for key in m1._defaults:
            d1, d2 = m1._defaults[key], m2._defaults[key]
            r1 = getattr(m1, "_reductions", {}).get(key)
            r2 = getattr(m2, "_reductions", {}).get(key)
            if r1 is not r2 and r1 != r2:
                return False
            if isinstance(d1, list) or isinstance(d2, list):
                if not (isinstance(d1, list) and isinstance(d2, list) and d1 == d2):
                    return False
                continue
            if d1 is d2:  # shared zero_state buffers — the common case
                continue
            if getattr(d1, "shape", None) != getattr(d2, "shape", None) or getattr(d1, "dtype", None) != getattr(
                d2, "dtype", None
            ):
                return False
            if not np.array_equal(np.asarray(d1), np.asarray(d2)):
                return False
        skip = set(m1._defaults) | cls._INSTANCE_ATTR_SKIP
        keys1 = {k for k in m1.__dict__ if k not in skip}
        keys2 = {k for k in m2.__dict__ if k not in skip}
        if keys1 != keys2:
            return False
        array_like = (jax.Array, np.ndarray, np.generic)
        for k in keys1:
            a, b = m1.__dict__[k], m2.__dict__[k]
            if a is b:
                continue
            if isinstance(a, array_like) or isinstance(b, array_like):
                if not (
                    isinstance(a, array_like)
                    and isinstance(b, array_like)
                    and getattr(a, "shape", None) == getattr(b, "shape", None)
                    and getattr(a, "dtype", None) == getattr(b, "dtype", None)
                    and np.array_equal(np.asarray(a), np.asarray(b))
                ):
                    return False
                continue
            if callable(a) or callable(b):
                return False
            try:
                if not bool(a == b):
                    return False
            except Exception:  # noqa: BLE001 — uncomparable config: keep apart
                return False
        return True

    # ------------------------------------------------------------------ dict protocol

    def keys(self, keep_base: bool = False) -> Iterable[str]:
        if keep_base:
            return self._modules.keys()
        return [self._set_name(k) for k in self._modules.keys()]

    def items(self, keep_base: bool = False, copy_state: bool = True) -> Iterable[Tuple[str, Metric]]:
        self._compute_groups_create_state_ref(copy_state)
        if keep_base:
            return self._modules.items()
        return [(self._set_name(k), v) for k, v in self._modules.items()]

    def values(self, copy_state: bool = True) -> Iterable[Metric]:
        self._compute_groups_create_state_ref(copy_state)
        return self._modules.values()

    def __getitem__(self, key: str, copy_state: bool = True) -> Metric:
        self._compute_groups_create_state_ref(copy_state)
        return self._modules[key]

    def __len__(self) -> int:
        return len(self._modules)

    def __iter__(self) -> Iterator[str]:
        return iter(self.keys())

    def __contains__(self, key: str) -> bool:
        return key in self._modules or key in list(self.keys())

    def _set_name(self, base: str) -> str:
        name = base if self.prefix is None else self.prefix + base
        name = name if self.postfix is None else name + self.postfix
        return name

    # ------------------------------------------------------------------ metric API

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update each metric once per compute group (reference :177-202).

        Only group leaders update — in the formation round too: structurally-
        seeded members provably evolve the leader's state, and their own
        first-update state would be discarded at the next
        _compute_groups_create_state_ref anyway, so the formation round skips
        the redundant member updates (VERDICT r4 #3) and the ported value
        merge arbitrates the remaining leaders. Group membership stays
        identical to the reference's.
        """
        # collection-level span: member updates nest under it in the trace, so a
        # Perfetto view shows which member dominates the collection's wall time
        with _obs.metric_op("update", self):
            for cg in self._groups.values():
                m0 = self._modules[cg[0]]
                m0.update(*args, **m0._filter_kwargs(**kwargs))
        if self._groups_checked:
            if self._state_is_copy:
                # If a copy was made, the aliasing is broken — restore it
                self._compute_groups_create_state_ref(copy=False)
                self._state_is_copy = False
        else:
            if self._enable_compute_groups and not isinstance(self._enable_compute_groups, list):
                self._merge_compute_groups()
            self._groups_checked = True

    def _merge_compute_groups(self) -> None:
        """O(n²) pairwise state comparison → merged groups (reference :204-238)."""
        num_groups = len(self._groups)
        while True:
            for cg_idx1, cg_members1 in deepcopy(self._groups).items():
                for cg_idx2, cg_members2 in deepcopy(self._groups).items():
                    if cg_idx1 == cg_idx2:
                        continue
                    metric1 = self._modules[cg_members1[0]]
                    metric2 = self._modules[cg_members2[0]]
                    if self._equal_metric_states(metric1, metric2):
                        self._groups[cg_idx1].extend(self._groups.pop(cg_idx2))
                        break
                else:
                    continue
                break
            else:
                break
            if len(self._groups) == num_groups:
                break
            num_groups = len(self._groups)

        # Re-index
        self._groups = {i: v for i, v in enumerate(self._groups.values())}

    @staticmethod
    def _equal_metric_states(metric1: Metric, metric2: Metric) -> bool:
        """Shape + allclose comparison of all states (reference :240-263)."""
        if len(metric1._defaults) == 0 or len(metric2._defaults) == 0:
            return False
        if metric1._defaults.keys() != metric2._defaults.keys():
            return False
        # numpy scalars/arrays appear as states on the eager host paths; they
        # compare interchangeably with jax arrays (value comparison, not type)
        array_like = (jax.Array, np.ndarray, np.generic)

        def _host_allclose(a, b) -> bool:
            # formation-round states are small; comparing on the host replaces
            # several eager device dispatches per pair (~200µs each on the
            # degraded CPU path) with a copy + np.allclose (~µs). Same
            # semantics as utils.data.allclose (NaN != NaN, as the reference).
            return bool(np.allclose(np.asarray(a), np.asarray(b)))

        for key in metric1._defaults:
            state1 = getattr(metric1, key)
            state2 = getattr(metric2, key)
            if isinstance(state1, array_like) and isinstance(state2, array_like):
                if state1.shape != state2.shape or state1.dtype != state2.dtype:
                    return False
                if not _host_allclose(state1, state2):
                    return False
            elif isinstance(state1, list) and isinstance(state2, list):
                if len(state1) != len(state2):
                    return False
                if not all(
                    s1.shape == s2.shape and _host_allclose(s1, s2) for s1, s2 in zip(state1, state2)
                ):
                    return False
            else:
                # mixed or unrecognised state kinds: never group on a guess
                return False
        return True

    def _compute_groups_create_state_ref(self, copy: bool = False) -> None:
        """Alias (or deepcopy) leader states onto group members (reference :265-282)."""
        if self._groups_checked:
            for cg in self._groups.values():
                m0 = self._modules[cg[0]]
                for name in cg[1:]:
                    mi = self._modules[name]
                    for state in m0._defaults:
                        m0_state = getattr(m0, state)
                        # alias the leader's state (immutable arrays: safe to share)
                        setattr(mi, state, deepcopy(m0_state) if copy else m0_state)
                    mi._update_count = deepcopy(m0._update_count) if copy else m0._update_count
                    mi._update_called = m0._update_called
                    # the member's compute cache predates the refreshed state
                    mi._computed = None
        self._state_is_copy = copy

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Per-batch value from every metric (reference :167-175).

        Beyond-parity: once compute groups are known, each group runs ONE
        forward (the leader's) and members derive their batch value from the
        leader's stashed batch-only state via their own ``compute``
        (`Metric._compute_batch_value`) — the training-loop hot path pays one
        update per GROUP, where the reference's forward always pays one update
        per METRIC even with groups formed (ref :167-175 iterates all).
        Exactly as sound as the grouped ``update``: members share the
        leader's state evolution by the group invariant both libraries rely
        on. Formation still happens in ``update`` (as in the reference —
        forward never forms groups in either library).
        """
        if self._groups_checked:
            by_name: Dict[str, Any] = {}
            for cg in self._groups.values():
                m0 = self._modules[cg[0]]
                by_name[cg[0]] = m0(*args, **m0._filter_kwargs(**kwargs))
                for name in cg[1:]:
                    mi = self._modules[name]
                    if m0._batch_state is not None:
                        by_name[name] = mi._compute_batch_value(m0._batch_state)
                    else:
                        # leader's forward didn't stash a batch state (custom
                        # forward override): member pays its own forward
                        by_name[name] = mi(*args, **mi._filter_kwargs(**kwargs))
            if self._state_is_copy:
                self._compute_groups_create_state_ref(copy=False)
                self._state_is_copy = False
            res = {k: by_name[k] for k in self._modules}
        else:
            res = {k: m(*args, **m._filter_kwargs(**kwargs)) for k, m in self._modules.items()}
        res, _ = _flatten_dict(res)
        return {self._set_name(k): v for k, v in res.items()}

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    def compute(self) -> Dict[str, Any]:
        """Compute every metric (group members see the leader's synced state)."""
        with _obs.metric_op("compute", self):
            self._compute_groups_create_state_ref()
            res = {k: m.compute() for k, m in self._modules.items()}
        res, _ = _flatten_dict(res)
        return {self._set_name(k): v for k, v in res.items()}

    def reset(self) -> None:
        for m in self._modules.values():
            m.reset()
        if self._enable_compute_groups and not isinstance(self._enable_compute_groups, list):
            # reset group detection: states are all equal (defaults) again
            self._groups_checked = False
            self._init_compute_groups()

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MetricCollection":
        """Deep copy with optionally new prefix/postfix (reference :~380)."""
        mc = deepcopy(self)
        if prefix is not None:
            mc.prefix = self._check_arg(prefix, "prefix")
        if postfix is not None:
            mc.postfix = self._check_arg(postfix, "postfix")
        return mc

    def persistent(self, mode: bool = True) -> None:
        for m in self._modules.values():
            m.persistent(mode)

    def state_dict(self, destination: Optional[Dict[str, Any]] = None, prefix: str = "") -> Dict[str, Any]:
        # group members may hold never-updated default states (only leaders
        # update) — refresh the aliasing so persistent states serialize with
        # their group's real values. destination/prefix mirror Metric's
        # signature so wrappers (MetricTracker) can nest collections.
        self._compute_groups_create_state_ref()
        destination = {} if destination is None else destination
        for name, m in self._modules.items():
            m.state_dict(destination, prefix=f"{prefix}{name}.")
        return destination

    def load_state_dict(
        self, state_dict: Dict[str, Any], prefix: str = "", strict: bool = True, _consumed: Optional[set] = None
    ) -> None:
        owns_check = _consumed is None
        consumed: set = set() if owns_check else _consumed
        for name, m in self._modules.items():
            m.load_state_dict(state_dict, prefix=f"{prefix}{name}.", strict=strict, _consumed=consumed)
        if owns_check and strict:
            _raise_on_unconsumed(state_dict, prefix, consumed)

    def to_device(self, device: Any) -> "MetricCollection":
        for m in self._modules.values():
            m.to_device(device)
        return self

    def save(self, path: str, *, policy: Any = None, meta: Optional[Dict[str, Any]] = None) -> None:
        """Persist the collection's FULL state (every member, every state) to
        ``path`` — atomic, checksummed, lossless by default. See
        :meth:`Metric.save`; group members serialize with their leader's real
        values (aliasing refreshed first, as in :meth:`state_dict`)."""
        from metrics_tpu.ckpt import save as _ckpt_save

        _ckpt_save(self, path, policy=policy, meta=meta)

    def restore(self, path: str) -> Any:
        """Load a :meth:`save` snapshot into this collection (strict — see
        :meth:`Metric.restore`). Compute-group aliasing is re-established after
        the load: members point at their leader's freshly restored arrays,
        never at stale pre-restore state."""
        from metrics_tpu.ckpt import restore as _ckpt_restore

        return _ckpt_restore(self, path)

    # ------------------------------------------------------------------ functional API (TPU-first)

    def init_state(self) -> Dict[str, Any]:
        """Per-group state pytree — structural dedup means one state per group."""
        if not self._groups_checked and self._enable_compute_groups:
            # without data we can't value-compare; fall back to per-metric states
            return {name: m.init_state() for name, m in self._modules.items()}
        return {cg[0]: self._modules[cg[0]].init_state() for cg in self._groups.values()}

    def update_state(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Pure group-deduped update — jit/shard_map safe."""
        new_state = {}
        for name, sub in state.items():
            m = self._modules[name]
            new_state[name] = m.update_state(sub, *args, **m._filter_kwargs(**kwargs))
        return new_state

    def merge_states(self, state_a: Dict[str, Any], state_b: Dict[str, Any]) -> Dict[str, Any]:
        """Associatively merge two collection state pytrees, per member metric.

        The collection analogue of :meth:`Metric.merge_states` — the streaming
        engine's sliding windows and cross-shard folds need it for collections too.
        States are keyed as ``init_state`` produced them (per metric, or per group
        leader once groups are known).
        """
        return {name: self._modules[name].merge_states(state_a[name], state_b[name]) for name in state_a}

    def jitted_update_state(self, donate: bool = True) -> Any:
        """Fused single-dispatch collection update (engine hook).

        ``update_state`` walks every group leader in Python; under ``jax.jit`` that
        whole walk fuses into ONE compiled dispatch updating every member state — the
        engine's collection path pays per-batch dispatch cost independent of the
        number of metrics. Donated state buffers as in
        :meth:`Metric.jitted_update_state`.
        """
        return _cached_jitted_updater(self, donate)

    def compute_from(self, state: Dict[str, Any], axis_name: Optional[Any] = None) -> Dict[str, Any]:
        """Pure compute for all metrics from the (group-deduped) state pytree."""
        leader_of = {}
        for cg in self._groups.values():
            for name in cg:
                leader_of[name] = cg[0] if cg[0] in state else name
        res = {}
        for name, m in self._modules.items():
            sub = state.get(name, state.get(leader_of.get(name, name)))
            res[name] = m.compute_from(sub, axis_name=axis_name)
        res, _ = _flatten_dict(res)
        return {self._set_name(k): v for k, v in res.items()}

    @property
    def compute_groups(self) -> Dict[int, List[str]]:
        return self._groups

    def __getstate__(self) -> Dict[str, Any]:
        # compiled executables (the jitted-updater cache) neither pickle nor deepcopy;
        # clone() rebuilds them lazily on first use. The obs instance label is dropped
        # so a clone gets its own telemetry series instead of aliasing its source's.
        return {
            k: v for k, v in self.__dict__.items()
            if k not in ("_jitted_update_state", "_obs_instance_label")
        }

    def __repr__(self) -> str:
        repr_str = self.__class__.__name__ + "(\n"
        for name, m in self._modules.items():
            repr_str += f"  ({name}): {m!r}\n"
        if self.prefix:
            repr_str += f"  prefix={self.prefix}\n"
        if self.postfix:
            repr_str += f"  postfix={self.postfix}\n"
        return repr_str + ")"
