"""Precision-recall-curve module metrics.

Reference parity: src/torchmetrics/classification/precision_recall_curve.py. Two state
regimes (reference docstring + :184-201): ``thresholds=None`` → exact, list states
(preds/target, all_gather'd); ``thresholds`` given → binned, a single fixed-shape
confmat state with sum-reduce (psum over mesh — constant memory, jit-native).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.precision_recall_curve import (
    _exact_mode_filter,
    Thresholds,
    _adjust_threshold_arg,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.enums import ClassificationTask


class BinaryPrecisionRecallCurve(Metric):
    """Precision-recall pairs at decision thresholds (exact or binned).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryPrecisionRecallCurve
        >>> probs = jnp.array([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> metric = BinaryPrecisionRecallCurve(thresholds=None)
        >>> metric.update(probs, target)
        >>> precision, recall, thresholds = metric.compute()
        >>> thresholds
        Array([0.22, 0.33, 0.73, 0.84, 0.92], dtype=float32)
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    preds: List[Array]
    target: List[Array]
    confmat: Array

    def __init__(
        self,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        self.ignore_index = ignore_index
        self.validate_args = validate_args

        thresholds = _adjust_threshold_arg(thresholds)
        if thresholds is None:
            self.thresholds = None
            self._host_compute = True
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")
        else:
            self.thresholds = thresholds
            self.add_state("confmat", zero_state((len(thresholds), 2, 2), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _binary_precision_recall_curve_tensor_validation(preds, target, self.ignore_index)
        preds, target, _, mask = _binary_precision_recall_curve_format(preds, target, self.thresholds, self.ignore_index)
        if self.thresholds is None:
            # eager: filter like the reference; in-trace: static-shape sentinel
            # fill that the host compute drops (_binary_clf_curve) — previously
            # a traced update silently kept ignored rows as negatives
            preds, target = _exact_mode_filter(preds, target, None, self.ignore_index, mask)
            self.preds.append(preds)
            self.target.append(target)
        else:
            self.confmat = self.confmat + _binary_precision_recall_curve_update(preds, target, self.thresholds, mask)

    def compute(self) -> Tuple[Array, Array, Array]:
        state = (dim_zero_cat(self.preds), dim_zero_cat(self.target)) if self.thresholds is None else self.confmat
        return _binary_precision_recall_curve_compute(state, self.thresholds)


class MulticlassPrecisionRecallCurve(Metric):
    """Multiclass Precision Recall Curve.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassPrecisionRecallCurve
        >>> metric = MulticlassPrecisionRecallCurve(num_classes=3, thresholds=4)
        >>> metric.update(jnp.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]]),
        ...               jnp.array([0, 1, 2, 1]))
        >>> precision, recall, thresholds = metric.compute()
        >>> precision.shape
        (3, 5)
    """
    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        num_classes: int,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
        self.num_classes = num_classes
        self.ignore_index = ignore_index
        self.validate_args = validate_args

        thresholds = _adjust_threshold_arg(thresholds)
        if thresholds is None:
            self.thresholds = None
            self._host_compute = True
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")
        else:
            self.thresholds = thresholds
            self.add_state(
                "confmat", zero_state((len(thresholds), num_classes, 2, 2), dtype=jnp.int32), dist_reduce_fx="sum"
            )

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multiclass_precision_recall_curve_tensor_validation(preds, target, self.num_classes, self.ignore_index)
        preds, target, _, mask = _multiclass_precision_recall_curve_format(
            preds, target, self.num_classes, self.thresholds, self.ignore_index
        )
        if self.thresholds is None:
            # see BinaryPrecisionRecallCurve.update on the sentinel path
            preds, target = _exact_mode_filter(preds, target, None, self.ignore_index, mask)
            self.preds.append(preds)
            self.target.append(target)
        else:
            self.confmat = self.confmat + _multiclass_precision_recall_curve_update(
                preds, target, self.num_classes, self.thresholds, mask
            )

    def compute(self):
        state = (dim_zero_cat(self.preds), dim_zero_cat(self.target)) if self.thresholds is None else self.confmat
        return _multiclass_precision_recall_curve_compute(state, self.num_classes, self.thresholds)


class MultilabelPrecisionRecallCurve(Metric):
    """Multilabel Precision Recall Curve.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelPrecisionRecallCurve
        >>> metric = MultilabelPrecisionRecallCurve(num_labels=3, thresholds=4)
        >>> metric.update(jnp.array([[0.9, 0.1, 0.7], [0.2, 0.8, 0.3], [0.6, 0.4, 0.2], [0.1, 0.7, 0.9]]),
        ...               jnp.array([[1, 0, 1], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> precision, recall, thresholds = metric.compute()
        >>> recall.shape
        (3, 5)
    """
    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        num_labels: int,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        self.num_labels = num_labels
        self.ignore_index = ignore_index
        self.validate_args = validate_args

        thresholds = _adjust_threshold_arg(thresholds)
        if thresholds is None:
            self.thresholds = None
            self._host_compute = True
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")
            self.add_state("mask", [], dist_reduce_fx="cat")
        else:
            self.thresholds = thresholds
            self.add_state(
                "confmat", zero_state((len(thresholds), num_labels, 2, 2), dtype=jnp.int32), dist_reduce_fx="sum"
            )

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multilabel_precision_recall_curve_tensor_validation(preds, target, self.num_labels, self.ignore_index)
        preds, target, _, mask = _multilabel_precision_recall_curve_format(
            preds, target, self.num_labels, self.thresholds, self.ignore_index
        )
        if self.thresholds is None:
            self.preds.append(preds)
            self.target.append(target)
            self.mask.append(mask)
        else:
            self.confmat = self.confmat + _multilabel_precision_recall_curve_update(
                preds, target, self.num_labels, self.thresholds, mask
            )

    def _curve_state(self):
        if self.thresholds is None:
            return (dim_zero_cat(self.preds), dim_zero_cat(self.target), dim_zero_cat(self.mask))
        return self.confmat

    def compute(self):
        return _multilabel_precision_recall_curve_compute(
            self._curve_state(), self.num_labels, self.thresholds, self.ignore_index
        )


class PrecisionRecallCurve:
    """Task façade (reference precision_recall_curve.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import PrecisionRecallCurve
        >>> metric = PrecisionRecallCurve(task="binary", thresholds=4)
        >>> metric.update(jnp.array([0.1, 0.6, 0.8, 0.4]), jnp.array([0, 1, 1, 0]))
        >>> precision, recall, thresholds = metric.compute()
        >>> precision
        Array([0.5      , 0.6666667, 1.       , 0.       , 1.       ], dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryPrecisionRecallCurve(**kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            return MulticlassPrecisionRecallCurve(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelPrecisionRecallCurve(num_labels, **kwargs)
        raise ValueError(f"Not handled value: {task}")
