"""Matthews correlation coefficient module metrics
(reference src/torchmetrics/classification/matthews_corrcoef.py)."""

from __future__ import annotations

from typing import Any, Optional

from jax import Array

from metrics_tpu.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from metrics_tpu.functional.classification.matthews_corrcoef import _matthews_corrcoef_reduce
from metrics_tpu.metric import Metric
from metrics_tpu.utils.enums import ClassificationTask


class BinaryMatthewsCorrCoef(BinaryConfusionMatrix):
    """Matthews correlation coefficient from the confusion matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryMatthewsCorrCoef
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> preds = jnp.array([0, 0, 1, 1, 0, 1])
        >>> metric = BinaryMatthewsCorrCoef()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.33333334, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(threshold, ignore_index, normalize=None, validate_args=validate_args, **kwargs)

    def compute(self) -> Array:
        return _matthews_corrcoef_reduce(self.confmat)


class MulticlassMatthewsCorrCoef(MulticlassConfusionMatrix):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_classes: int,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes, ignore_index, normalize=None, validate_args=validate_args, **kwargs)

    def compute(self) -> Array:
        return _matthews_corrcoef_reduce(self.confmat)


class MultilabelMatthewsCorrCoef(MultilabelConfusionMatrix):
    """Multilabel Matthews Corr Coef.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelMatthewsCorrCoef
        >>> metric = MultilabelMatthewsCorrCoef(num_labels=3)
        >>> metric.update(jnp.array([[1, 0, 1], [0, 1, 0], [1, 1, 0], [0, 0, 1]]),
        ...               jnp.array([[1, 0, 0], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> metric.compute()
        Array(0.50709254, dtype=float32)
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_labels, threshold, ignore_index, normalize=None, validate_args=validate_args, **kwargs)

    def compute(self) -> Array:
        return _matthews_corrcoef_reduce(self.confmat)


class MatthewsCorrCoef:
    """Task façade (reference matthews_corrcoef.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MatthewsCorrCoef
        >>> metric = MatthewsCorrCoef(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array(0.7, dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryMatthewsCorrCoef(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            return MulticlassMatthewsCorrCoef(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelMatthewsCorrCoef(num_labels, threshold, **kwargs)
        raise ValueError(f"Not handled value: {task}")
