"""Jaccard index module metrics (reference src/torchmetrics/classification/jaccard.py)."""

from __future__ import annotations

from typing import Any, Optional

from jax import Array

from metrics_tpu.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from metrics_tpu.functional.classification.jaccard import _jaccard_index_reduce
from metrics_tpu.metric import Metric
from metrics_tpu.utils.enums import ClassificationTask


class BinaryJaccardIndex(BinaryConfusionMatrix):
    """Intersection-over-union from the 2x2 confusion matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryJaccardIndex
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> preds = jnp.array([0, 0, 1, 1, 0, 1])
        >>> metric = BinaryJaccardIndex()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.5, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        return _jaccard_index_reduce(self.confmat, average="binary")


class MulticlassJaccardIndex(MulticlassConfusionMatrix):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_classes: int,
        average: Optional[str] = "macro",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes, ignore_index, normalize=None, validate_args=validate_args, **kwargs)
        self.average = average

    def compute(self) -> Array:
        return _jaccard_index_reduce(self.confmat, average=self.average, ignore_index=self.ignore_index)


class MultilabelJaccardIndex(MultilabelConfusionMatrix):
    """Multilabel Jaccard Index.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelJaccardIndex
        >>> metric = MultilabelJaccardIndex(num_labels=3)
        >>> metric.update(jnp.array([[1, 0, 1], [0, 1, 0], [1, 1, 0], [0, 0, 1]]),
        ...               jnp.array([[1, 0, 0], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> metric.compute()
        Array(0.6111111, dtype=float32)
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        average: Optional[str] = "macro",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_labels, threshold, ignore_index, normalize=None, validate_args=validate_args, **kwargs)
        self.average = average

    def compute(self) -> Array:
        return _jaccard_index_reduce(self.confmat, average=self.average)


class JaccardIndex:
    """Task façade (reference jaccard.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import JaccardIndex
        >>> metric = JaccardIndex(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array(0.6666667, dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "macro",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryJaccardIndex(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            return MulticlassJaccardIndex(num_classes, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelJaccardIndex(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
