"""Hamming distance module metrics (reference src/torchmetrics/classification/hamming.py)."""

from __future__ import annotations

from typing import Any, Optional

from jax import Array

from metrics_tpu.classification.stat_scores import BinaryStatScores, MulticlassStatScores, MultilabelStatScores
from metrics_tpu.functional.classification.hamming import _hamming_distance_reduce
from metrics_tpu.metric import Metric
from metrics_tpu.utils.enums import ClassificationTask


class BinaryHammingDistance(BinaryStatScores):
    """Fraction of disagreeing labels (1 - accuracy for binary).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryHammingDistance
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> preds = jnp.array([0, 0, 1, 1, 0, 1])
        >>> metric = BinaryHammingDistance()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.3333333, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _hamming_distance_reduce(tp, fp, tn, fn, average="binary", multidim_average=self.multidim_average)


class MulticlassHammingDistance(MulticlassStatScores):
    """Multiclass Hamming Distance.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassHammingDistance
        >>> metric = MulticlassHammingDistance(num_classes=3)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array(0.16666667, dtype=float32)
    """
    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _hamming_distance_reduce(tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average)


class MultilabelHammingDistance(MultilabelStatScores):
    """Multilabel Hamming Distance.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelHammingDistance
        >>> metric = MultilabelHammingDistance(num_labels=3)
        >>> metric.update(jnp.array([[1, 0, 1], [0, 1, 0], [1, 1, 0], [0, 0, 1]]),
        ...               jnp.array([[1, 0, 0], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> metric.compute()
        Array(0.25, dtype=float32)
    """
    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _hamming_distance_reduce(tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average, multilabel=True)


class HammingDistance:
    """Task façade (reference hamming.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import HammingDistance
        >>> metric = HammingDistance(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array(0.25, dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: int = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryHammingDistance(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            assert isinstance(top_k, int)
            return MulticlassHammingDistance(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelHammingDistance(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
