"""Multilabel ranking module metrics.

Reference parity: src/torchmetrics/classification/ranking.py
(MultilabelCoverageError / MultilabelRankingAveragePrecision / MultilabelRankingLoss).
Scalar (measure-sum, sample-count) states with sum-reduce — psum over the mesh.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.ranking import (
    _multilabel_coverage_error_update,
    _multilabel_ranking_arg_validation,
    _multilabel_ranking_average_precision_update,
    _multilabel_ranking_format,
    _multilabel_ranking_loss_update,
    _multilabel_ranking_tensor_validation,
    _ranking_reduce,
)
from metrics_tpu.metric import Metric, zero_state


class _MultilabelRankingMetric(Metric):
    """Shared shell: format inputs, accumulate (measure, total)."""

    is_differentiable = False
    full_state_update = False

    measure: Array
    total: Array

    _update_fn = None  # set by subclasses

    def __init__(
        self,
        num_labels: int,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_ranking_arg_validation(num_labels, ignore_index)
        self.num_labels = num_labels
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("measure", zero_state((), dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", zero_state((), dtype=jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multilabel_ranking_tensor_validation(preds, target, self.num_labels, self.ignore_index)
        preds, target, _ = _multilabel_ranking_format(preds, target, self.num_labels, self.ignore_index)
        measure, total = type(self)._update_fn(preds, target)
        self.measure = self.measure + measure
        self.total = self.total + total

    def compute(self) -> Array:
        return _ranking_reduce(self.measure, self.total)


class MultilabelCoverageError(_MultilabelRankingMetric):
    """Multilabel Coverage Error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelCoverageError
        >>> metric = MultilabelCoverageError(num_labels=3)
        >>> metric.update(jnp.array([[0.9, 0.1, 0.7], [0.2, 0.8, 0.3], [0.6, 0.4, 0.2], [0.1, 0.7, 0.9]]),
        ...               jnp.array([[1, 0, 1], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> metric.compute()
        Array(1.5, dtype=float32)
    """
    higher_is_better = False
    _update_fn = staticmethod(_multilabel_coverage_error_update)


class MultilabelRankingAveragePrecision(_MultilabelRankingMetric):
    """Label-ranking average precision.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelRankingAveragePrecision
        >>> preds = jnp.array([[0.9, 0.1, 0.8], [0.3, 0.7, 0.2]])
        >>> target = jnp.array([[1, 0, 1], [0, 1, 0]])
        >>> metric = MultilabelRankingAveragePrecision(num_labels=3)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    higher_is_better = True
    _update_fn = staticmethod(_multilabel_ranking_average_precision_update)


class MultilabelRankingLoss(_MultilabelRankingMetric):
    """Multilabel Ranking Loss.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelRankingLoss
        >>> metric = MultilabelRankingLoss(num_labels=3)
        >>> metric.update(jnp.array([[0.9, 0.1, 0.7], [0.2, 0.8, 0.3], [0.6, 0.4, 0.2], [0.1, 0.7, 0.9]]),
        ...               jnp.array([[1, 0, 1], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> metric.compute()
        Array(0., dtype=float32)
    """
    higher_is_better = False
    _update_fn = staticmethod(_multilabel_ranking_loss_update)
