"""Stat-scores module metrics.

Reference parity: src/torchmetrics/classification/stat_scores.py —
``_AbstractStatScores._create_state/_update_state/_final_state`` (:41-81),
``BinaryStatScores`` (:84+), ``MulticlassStatScores``, ``MultilabelStatScores``, and the
legacy task-dispatch façade ``StatScores.__new__`` (:485-513).

State regimes (reference :62-81): ``multidim_average="global"`` → fixed-shape tensor
states with ``dist_reduce_fx="sum"`` (psum over the mesh — no gather);
``"samplewise"`` → ragged list states with ``"cat"`` (all_gather).
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_compute,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
    _binary_stat_scores_update,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_compute,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multiclass_stat_scores_update,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_compute,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
    _multilabel_stat_scores_update,
)
from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.enums import ClassificationTask


class _AbstractStatScores(Metric):
    """Shared tp/fp/tn/fn state plumbing (reference classification/stat_scores.py:41)."""

    tp: Any
    fp: Any
    tn: Any
    fn: Any

    def _create_state(self, size: int, multidim_average: str = "global") -> None:
        """Initialize states: tensor+sum for global, list+cat for samplewise."""
        if multidim_average == "samplewise":
            default: Any = lambda: []
            reduce_fx = "cat"
            for s in ("tp", "fp", "tn", "fn"):
                self.add_state(s, [], dist_reduce_fx=reduce_fx)
        else:
            shape = () if size == 1 else (size,)
            for s in ("tp", "fp", "tn", "fn"):
                self.add_state(s, zero_state(shape, dtype=jnp.int32), dist_reduce_fx="sum")

    def _update_state(self, tp: Array, fp: Array, tn: Array, fn: Array) -> None:
        """Accumulate (+= for tensor states, append for list states)."""
        if isinstance(self.tp, list):
            self.tp.append(tp)
            self.fp.append(fp)
            self.tn.append(tn)
            self.fn.append(fn)
        else:
            self.tp = self.tp + tp
            self.fp = self.fp + fp
            self.tn = self.tn + tn
            self.fn = self.fn + fn

    def _final_state(self):
        """Final aggregated state (cat list states)."""
        tp = dim_zero_cat(self.tp) if isinstance(self.tp, list) else self.tp
        fp = dim_zero_cat(self.fp) if isinstance(self.fp, list) else self.fp
        tn = dim_zero_cat(self.tn) if isinstance(self.tn, list) else self.tn
        fn = dim_zero_cat(self.fn) if isinstance(self.fn, list) else self.fn
        return tp, fp, tn, fn


class BinaryStatScores(_AbstractStatScores):
    """tp/fp/tn/fn counts plus support (reference classification/stat_scores.py:84-192).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryStatScores
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> preds = jnp.array([0, 0, 1, 1, 0, 1])
        >>> metric = BinaryStatScores()
        >>> metric.update(preds, target)
        >>> metric.compute()  # [tp, fp, tn, fn, support]
        Array([2, 1, 2, 1, 3], dtype=int32)
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        threshold: float = 0.5,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        self.threshold = threshold
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(size=1, multidim_average=multidim_average)

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _binary_stat_scores_tensor_validation(preds, target, self.multidim_average, self.ignore_index)
        preds, target, mask = _binary_stat_scores_format(preds, target, self.threshold, self.ignore_index)
        tp, fp, tn, fn = _binary_stat_scores_update(preds, target, mask, self.multidim_average)
        self._update_state(tp, fp, tn, fn)

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _binary_stat_scores_compute(tp, fp, tn, fn, self.multidim_average)


class MulticlassStatScores(_AbstractStatScores):
    """Reference classification/stat_scores.py:195-321.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassStatScores
        >>> metric = MulticlassStatScores(num_classes=3)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array([[1, 0, 3, 0, 1],
               [1, 0, 2, 1, 2],
               [1, 1, 2, 0, 1]], dtype=int32)
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        num_classes: int,
        top_k: int = 1,
        average: Optional[str] = "macro",
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        self.num_classes = num_classes
        self.top_k = top_k
        self.average = average
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        # states are always per-class (C,) — unlike the reference's size-1 micro
        # optimisation (stat_scores.py:195-321), the micro sum happens in compute; a
        # C-sized psum costs the same as a scalar one on TPU and removes shape special
        # cases from every derived reduce formula.
        self._create_state(size=num_classes, multidim_average=multidim_average)

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multiclass_stat_scores_tensor_validation(preds, target, self.num_classes, self.multidim_average, self.ignore_index)
        preds, target = _multiclass_stat_scores_format(preds, target, self.top_k)
        tp, fp, tn, fn = _multiclass_stat_scores_update(
            preds, target, self.num_classes, self.top_k, self.average, self.multidim_average, self.ignore_index
        )
        self._update_state(tp, fp, tn, fn)

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _multiclass_stat_scores_compute(tp, fp, tn, fn, self.average, self.multidim_average)


class MultilabelStatScores(_AbstractStatScores):
    """Reference classification/stat_scores.py:324-455.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelStatScores
        >>> metric = MultilabelStatScores(num_labels=3)
        >>> metric.update(jnp.array([[1, 0, 1], [0, 1, 0], [1, 1, 0], [0, 0, 1]]),
        ...               jnp.array([[1, 0, 0], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> metric.compute()
        Array([[2, 0, 2, 0, 2],
               [1, 1, 1, 1, 2],
               [1, 1, 2, 0, 1]], dtype=int32)
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        average: Optional[str] = "macro",
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        self.num_labels = num_labels
        self.threshold = threshold
        self.average = average
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(size=num_labels, multidim_average=multidim_average)

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multilabel_stat_scores_tensor_validation(preds, target, self.num_labels, self.multidim_average, self.ignore_index)
        preds, target, mask = _multilabel_stat_scores_format(preds, target, self.num_labels, self.threshold, self.ignore_index)
        tp, fp, tn, fn = _multilabel_stat_scores_update(preds, target, mask, self.multidim_average)
        self._update_state(tp, fp, tn, fn)

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _multilabel_stat_scores_compute(tp, fp, tn, fn, self.average, self.multidim_average)


class StatScores:
    """Task-dispatch façade — ``__new__`` returns the task-specific metric.

    Reference classification/stat_scores.py:485-513.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import StatScores
        >>> metric = StatScores(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array([3, 1, 7, 1, 4], dtype=int32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: int = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        assert multidim_average is not None
        kwargs.update({"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryStatScores(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            assert isinstance(top_k, int)
            return MulticlassStatScores(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelStatScores(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
