"""AUROC module metrics (reference src/torchmetrics/classification/auroc.py)."""

from __future__ import annotations

from typing import Any, Optional

from jax import Array

from metrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from metrics_tpu.functional.classification.auroc import (
    _binary_auroc_compute,
    _multiclass_auroc_compute,
    _multilabel_auroc_compute,
)
from metrics_tpu.functional.classification.precision_recall_curve import Thresholds
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.enums import ClassificationTask


class BinaryAUROC(BinaryPrecisionRecallCurve):
    """Area under the ROC curve; exact (thresholds=None) or binned.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryAUROC
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> probs = jnp.array([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> metric = BinaryAUROC(thresholds=None)
        >>> metric.update(probs, target)
        >>> metric.compute()
        Array(0.6666667, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        max_fpr: Optional[float] = None,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(thresholds=thresholds, ignore_index=ignore_index, validate_args=validate_args, **kwargs)
        if validate_args and max_fpr is not None and not (isinstance(max_fpr, float) and 0 < max_fpr <= 1):
            raise ValueError(f"Arguments `max_fpr` should be a float in range (0, 1], but got: {max_fpr}")
        self.max_fpr = max_fpr

    def compute(self) -> Array:
        state = (dim_zero_cat(self.preds), dim_zero_cat(self.target)) if self.thresholds is None else self.confmat
        return _binary_auroc_compute(state, self.thresholds, self.max_fpr)


class MulticlassAUROC(MulticlassPrecisionRecallCurve):
    """Multiclass A U R O C.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassAUROC
        >>> metric = MulticlassAUROC(num_classes=3)
        >>> metric.update(jnp.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]]),
        ...               jnp.array([0, 1, 2, 1]))
        >>> metric.compute()
        Array(1., dtype=float32)
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_classes: int,
        average: Optional[str] = "macro",
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes=num_classes, thresholds=thresholds, ignore_index=ignore_index,
                         validate_args=validate_args, **kwargs)
        if validate_args:
            allowed_average = ("macro", "weighted", "none", None)
            if average not in allowed_average:
                raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
        self.average = average

    def compute(self) -> Array:
        state = (dim_zero_cat(self.preds), dim_zero_cat(self.target)) if self.thresholds is None else self.confmat
        return _multiclass_auroc_compute(state, self.num_classes, self.average, self.thresholds)


class MultilabelAUROC(MultilabelPrecisionRecallCurve):
    """Multilabel A U R O C.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelAUROC
        >>> metric = MultilabelAUROC(num_labels=3)
        >>> metric.update(jnp.array([[0.9, 0.1, 0.7], [0.2, 0.8, 0.3], [0.6, 0.4, 0.2], [0.1, 0.7, 0.9]]),
        ...               jnp.array([[1, 0, 1], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> metric.compute()
        Array(1., dtype=float32)
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_labels: int,
        average: Optional[str] = "macro",
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index,
                         validate_args=validate_args, **kwargs)
        if validate_args:
            allowed_average = ("micro", "macro", "weighted", "none", None)
            if average not in allowed_average:
                raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
        self.average = average

    def compute(self) -> Array:
        if self.thresholds is None:
            state = (dim_zero_cat(self.preds), dim_zero_cat(self.target), dim_zero_cat(self.mask))
        else:
            state = self.confmat
        return _multilabel_auroc_compute(state, self.num_labels, self.average, self.thresholds, self.ignore_index)


class AUROC:
    """Task façade (reference auroc.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import AUROC
        >>> metric = AUROC(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]]),
        ...               jnp.array([0, 1, 2, 1]))
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "macro",
        max_fpr: Optional[float] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryAUROC(max_fpr, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            return MulticlassAUROC(num_classes, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelAUROC(num_labels, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
