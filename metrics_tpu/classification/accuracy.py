"""Accuracy module metrics.

Reference parity: src/torchmetrics/classification/accuracy.py (Binary/Multiclass/
Multilabel subclasses of the stat-scores family + ``Accuracy.__new__`` façade).
"""

from __future__ import annotations

from typing import Any, Optional

from jax import Array

from metrics_tpu.classification.stat_scores import BinaryStatScores, MulticlassStatScores, MultilabelStatScores
from metrics_tpu.functional.classification.accuracy import _accuracy_reduce
from metrics_tpu.metric import Metric
from metrics_tpu.utils.enums import ClassificationTask


class BinaryAccuracy(BinaryStatScores):
    """Binary accuracy over tp/fp/tn/fn sum states (reference accuracy.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryAccuracy
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> preds = jnp.array([0, 0, 1, 1, 0, 1])
        >>> metric = BinaryAccuracy()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.6666667, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _accuracy_reduce(tp, fp, tn, fn, average="binary", multidim_average=self.multidim_average)


class MulticlassAccuracy(MulticlassStatScores):
    """Multiclass accuracy with micro/macro/weighted/none averaging.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassAccuracy
        >>> target = jnp.array([2, 1, 0, 1])
        >>> probs = jnp.array([[0.16, 0.26, 0.58],
        ...                    [0.22, 0.61, 0.17],
        ...                    [0.71, 0.09, 0.20],
        ...                    [0.05, 0.82, 0.13]])
        >>> metric = MulticlassAccuracy(num_classes=3)
        >>> metric.update(probs, target)
        >>> metric.compute()
        Array(1., dtype=float32)
        >>> per_class = MulticlassAccuracy(num_classes=3, average=None)
        >>> per_class.update(probs, target)
        >>> per_class.compute()
        Array([1., 1., 1.], dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _accuracy_reduce(tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average)


class MultilabelAccuracy(MultilabelStatScores):
    """Multilabel accuracy: per-label threshold at 0.5 by default.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelAccuracy
        >>> target = jnp.array([[0, 1, 0], [1, 0, 1]])
        >>> preds = jnp.array([[0.11, 0.58, 0.22], [0.84, 0.73, 0.33]])
        >>> metric = MultilabelAccuracy(num_labels=3)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.6666667, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _accuracy_reduce(tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average, multilabel=True)


class Accuracy:
    """Task façade (reference accuracy.py ``Accuracy.__new__``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import Accuracy
        >>> metric = Accuracy(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array(0.75, dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: int = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryAccuracy(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            assert isinstance(top_k, int)
            return MulticlassAccuracy(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelAccuracy(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
