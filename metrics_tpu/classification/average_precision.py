"""Average-precision module metrics (reference src/torchmetrics/classification/average_precision.py)."""

from __future__ import annotations

from typing import Any, Optional

from jax import Array

from metrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from metrics_tpu.functional.classification.average_precision import (
    _binary_average_precision_compute,
    _multiclass_average_precision_compute,
    _multilabel_average_precision_compute,
)
from metrics_tpu.functional.classification.precision_recall_curve import Thresholds
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.enums import ClassificationTask


class BinaryAveragePrecision(BinaryPrecisionRecallCurve):
    """Area under the precision-recall curve.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryAveragePrecision
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> probs = jnp.array([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> metric = BinaryAveragePrecision(thresholds=None)
        >>> metric.update(probs, target)
        >>> metric.compute()
        Array(0.7555556, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        state = (dim_zero_cat(self.preds), dim_zero_cat(self.target)) if self.thresholds is None else self.confmat
        return _binary_average_precision_compute(state, self.thresholds)


class MulticlassAveragePrecision(MulticlassPrecisionRecallCurve):
    """Multiclass Average Precision.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassAveragePrecision
        >>> metric = MulticlassAveragePrecision(num_classes=3)
        >>> metric.update(jnp.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]]),
        ...               jnp.array([0, 1, 2, 1]))
        >>> metric.compute()
        Array(1., dtype=float32)
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_classes: int,
        average: Optional[str] = "macro",
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes=num_classes, thresholds=thresholds, ignore_index=ignore_index,
                         validate_args=validate_args, **kwargs)
        if validate_args:
            allowed_average = ("macro", "weighted", "none", None)
            if average not in allowed_average:
                raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
        self.average = average

    def compute(self) -> Array:
        state = (dim_zero_cat(self.preds), dim_zero_cat(self.target)) if self.thresholds is None else self.confmat
        return _multiclass_average_precision_compute(state, self.num_classes, self.average, self.thresholds)


class MultilabelAveragePrecision(MultilabelPrecisionRecallCurve):
    """Multilabel Average Precision.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelAveragePrecision
        >>> metric = MultilabelAveragePrecision(num_labels=3)
        >>> metric.update(jnp.array([[0.9, 0.1, 0.7], [0.2, 0.8, 0.3], [0.6, 0.4, 0.2], [0.1, 0.7, 0.9]]),
        ...               jnp.array([[1, 0, 1], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> metric.compute()
        Array(1., dtype=float32)
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_labels: int,
        average: Optional[str] = "macro",
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index,
                         validate_args=validate_args, **kwargs)
        if validate_args:
            allowed_average = ("micro", "macro", "weighted", "none", None)
            if average not in allowed_average:
                raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
        self.average = average

    def compute(self) -> Array:
        if self.thresholds is None:
            state = (dim_zero_cat(self.preds), dim_zero_cat(self.target), dim_zero_cat(self.mask))
        else:
            state = self.confmat
        return _multilabel_average_precision_compute(state, self.num_labels, self.average, self.thresholds, self.ignore_index)


class AveragePrecision:
    """Task façade (reference average_precision.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import AveragePrecision
        >>> metric = AveragePrecision(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]]),
        ...               jnp.array([0, 1, 2, 1]))
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "macro",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryAveragePrecision(**kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            return MulticlassAveragePrecision(num_classes, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelAveragePrecision(num_labels, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
