"""Hinge-loss module metrics.

Reference parity: src/torchmetrics/classification/hinge.py
(BinaryHingeLoss / MulticlassHingeLoss + ``HingeLoss`` façade). Scalar sum states
(``measures``/``total``) with sum-reduce — psum over the mesh.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.hinge import (
    _binary_hinge_loss_arg_validation,
    _binary_hinge_loss_tensor_validation,
    _binary_hinge_loss_update,
    _hinge_loss_compute,
    _multiclass_hinge_loss_arg_validation,
    _multiclass_hinge_loss_tensor_validation,
    _multiclass_hinge_loss_update,
)
from metrics_tpu.functional.classification.stat_scores import _ignore_mask, _sigmoid_if_logits
from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.enums import ClassificationTaskNoMultilabel


class BinaryHingeLoss(Metric):
    """Binary hinge loss.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryHingeLoss
        >>> metric = BinaryHingeLoss()
        >>> metric.update(jnp.array([0.9, 0.1, 0.8]), jnp.array([1, 0, 1]))
        >>> metric.compute()
        Array(0.4666667, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    measures: Array
    total: Array

    def __init__(
        self,
        squared: bool = False,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_hinge_loss_arg_validation(squared, ignore_index)
        self.squared = squared
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("measures", zero_state((), dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", zero_state((), dtype=jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _binary_hinge_loss_tensor_validation(preds, target, self.ignore_index)
        preds = jnp.asarray(preds).reshape(-1).astype(jnp.float32)
        target = jnp.asarray(target).reshape(-1)
        mask = _ignore_mask(target, self.ignore_index).reshape(-1)
        target = jnp.where(mask, target, 0)
        preds = _sigmoid_if_logits(preds)
        measures, total = _binary_hinge_loss_update(preds, target, self.squared, mask)
        self.measures = self.measures + measures
        self.total = self.total + total

    def compute(self) -> Array:
        return _hinge_loss_compute(self.measures, self.total)


class MulticlassHingeLoss(Metric):
    """Multiclass Hinge Loss.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassHingeLoss
        >>> metric = MulticlassHingeLoss(num_classes=3)
        >>> metric.update(jnp.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]]),
        ...               jnp.array([0, 1, 2, 1]))
        >>> metric.compute()
        Array(0.625, dtype=float32)
    """
    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    measures: Array
    total: Array

    def __init__(
        self,
        num_classes: int,
        squared: bool = False,
        multiclass_mode: str = "crammer-singer",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_hinge_loss_arg_validation(num_classes, squared, multiclass_mode, ignore_index)
        self.num_classes = num_classes
        self.squared = squared
        self.multiclass_mode = multiclass_mode
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        shape = () if multiclass_mode == "crammer-singer" else (num_classes,)
        self.add_state("measures", zero_state(shape, dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", zero_state((), dtype=jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multiclass_hinge_loss_tensor_validation(preds, target, self.num_classes, self.ignore_index)
        preds = jnp.moveaxis(jnp.asarray(preds), 1, -1).reshape(-1, self.num_classes).astype(jnp.float32)
        target = jnp.asarray(target).reshape(-1)
        mask = _ignore_mask(target, self.ignore_index)
        target = jnp.where(mask, target, 0)
        measures, total = _multiclass_hinge_loss_update(preds, target, self.squared, self.multiclass_mode, mask)
        self.measures = self.measures + measures
        self.total = self.total + total

    def compute(self) -> Array:
        return _hinge_loss_compute(self.measures, self.total)


class HingeLoss:
    """Task façade (reference hinge.py ``HingeLoss.__new__``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import HingeLoss
        >>> metric = HingeLoss(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]]),
        ...               jnp.array([0, 1, 2, 1]))
        >>> metric.compute()
        Array(0.625, dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        num_classes: Optional[int] = None,
        squared: bool = False,
        multiclass_mode: str = "crammer-singer",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTaskNoMultilabel.from_str_or_raise(task)
        kwargs.update({"ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTaskNoMultilabel.BINARY:
            return BinaryHingeLoss(squared, **kwargs)
        if task == ClassificationTaskNoMultilabel.MULTICLASS:
            assert isinstance(num_classes, int)
            return MulticlassHingeLoss(num_classes, squared, multiclass_mode, **kwargs)
        raise ValueError(f"Not handled value: {task}")
