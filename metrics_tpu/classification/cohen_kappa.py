"""Cohen's kappa module metrics (reference src/torchmetrics/classification/cohen_kappa.py)."""

from __future__ import annotations

from typing import Any, Optional

from jax import Array

from metrics_tpu.classification.confusion_matrix import BinaryConfusionMatrix, MulticlassConfusionMatrix
from metrics_tpu.functional.classification.cohen_kappa import _cohen_kappa_reduce
from metrics_tpu.metric import Metric
from metrics_tpu.utils.enums import ClassificationTask


class BinaryCohenKappa(BinaryConfusionMatrix):
    """Cohen's kappa: agreement corrected for chance.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryCohenKappa
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> preds = jnp.array([0, 0, 1, 1, 0, 1])
        >>> metric = BinaryCohenKappa()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.3333333, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        weights: Optional[str] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(threshold, ignore_index, normalize=None, validate_args=validate_args, **kwargs)
        self.weights = weights

    def compute(self) -> Array:
        return _cohen_kappa_reduce(self.confmat, self.weights)


class MulticlassCohenKappa(MulticlassConfusionMatrix):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_classes: int,
        ignore_index: Optional[int] = None,
        weights: Optional[str] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes, ignore_index, normalize=None, validate_args=validate_args, **kwargs)
        self.weights = weights

    def compute(self) -> Array:
        return _cohen_kappa_reduce(self.confmat, self.weights)


class CohenKappa:
    """Task façade (reference cohen_kappa.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import CohenKappa
        >>> metric = CohenKappa(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array(0.6363636, dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        weights: Optional[str] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"weights": weights, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryCohenKappa(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            return MulticlassCohenKappa(num_classes, **kwargs)
        raise ValueError(f"Expected argument `task` to either be 'binary' or 'multiclass' but got {task}")
