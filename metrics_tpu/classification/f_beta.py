"""F-beta / F1 module metrics.

Reference parity: src/torchmetrics/classification/f_beta.py.
"""

from __future__ import annotations

from typing import Any, Optional

from jax import Array

from metrics_tpu.classification.stat_scores import BinaryStatScores, MulticlassStatScores, MultilabelStatScores
from metrics_tpu.functional.classification.f_beta import _fbeta_reduce, _validate_beta
from metrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _multiclass_stat_scores_arg_validation,
    _multilabel_stat_scores_arg_validation,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.enums import ClassificationTask


class BinaryFBetaScore(BinaryStatScores):
    """F-beta for binary tasks over tp/fp/tn/fn sum states.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryFBetaScore
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> preds = jnp.array([0, 0, 1, 1, 0, 1])
        >>> metric = BinaryFBetaScore(beta=2.0)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.6666667, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, beta: float, threshold: float = 0.5, multidim_average: str = "global",
                 ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(threshold=threshold, multidim_average=multidim_average, ignore_index=ignore_index,
                         validate_args=False, **kwargs)
        if validate_args:
            _validate_beta(beta)
            _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        self.validate_args = validate_args
        self.beta = beta

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _fbeta_reduce(tp, fp, tn, fn, self.beta, average="binary", multidim_average=self.multidim_average)


class MulticlassFBetaScore(MulticlassStatScores):
    """Multiclass F-beta Score.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassFBetaScore
        >>> metric = MulticlassFBetaScore(num_classes=3, beta=0.5)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array(0.79629636, dtype=float32)
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, beta: float, num_classes: int, top_k: int = 1, average: Optional[str] = "macro",
                 multidim_average: str = "global", ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(num_classes=num_classes, top_k=top_k, average=average, multidim_average=multidim_average,
                         ignore_index=ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _validate_beta(beta)
            _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        self.validate_args = validate_args
        self.beta = beta

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _fbeta_reduce(tp, fp, tn, fn, self.beta, average=self.average, multidim_average=self.multidim_average)


class MultilabelFBetaScore(MultilabelStatScores):
    """Multilabel F-beta Score.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelFBetaScore
        >>> metric = MultilabelFBetaScore(num_labels=3, beta=0.5)
        >>> metric.update(jnp.array([[1, 0, 1], [0, 1, 0], [1, 1, 0], [0, 0, 1]]),
        ...               jnp.array([[1, 0, 0], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> metric.compute()
        Array(0.6851852, dtype=float32)
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, beta: float, num_labels: int, threshold: float = 0.5, average: Optional[str] = "macro",
                 multidim_average: str = "global", ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(num_labels=num_labels, threshold=threshold, average=average,
                         multidim_average=multidim_average, ignore_index=ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _validate_beta(beta)
            _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        self.validate_args = validate_args
        self.beta = beta

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _fbeta_reduce(tp, fp, tn, fn, self.beta, average=self.average, multidim_average=self.multidim_average, multilabel=True)


class BinaryF1Score(BinaryFBetaScore):
    """F1 (harmonic precision/recall mean) for binary tasks.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryF1Score
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> preds = jnp.array([0, 0, 1, 1, 0, 1])
        >>> metric = BinaryF1Score()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.6666667, dtype=float32)
    """

    def __init__(self, threshold: float = 0.5, multidim_average: str = "global",
                 ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(beta=1.0, threshold=threshold, multidim_average=multidim_average,
                         ignore_index=ignore_index, validate_args=validate_args, **kwargs)


class MulticlassF1Score(MulticlassFBetaScore):
    """Macro-averaged multiclass F1 by default.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassF1Score
        >>> target = jnp.array([2, 1, 0, 1])
        >>> preds = jnp.array([2, 1, 0, 1])
        >>> metric = MulticlassF1Score(num_classes=3)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    def __init__(self, num_classes: int, top_k: int = 1, average: Optional[str] = "macro",
                 multidim_average: str = "global", ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(beta=1.0, num_classes=num_classes, top_k=top_k, average=average,
                         multidim_average=multidim_average, ignore_index=ignore_index,
                         validate_args=validate_args, **kwargs)


class MultilabelF1Score(MultilabelFBetaScore):
    """Per-label F1, macro-averaged by default.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelF1Score
        >>> target = jnp.array([[0, 1, 0], [1, 0, 1]])
        >>> preds = jnp.array([[0.11, 0.58, 0.22], [0.84, 0.73, 0.33]])
        >>> metric = MultilabelF1Score(num_labels=3)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.5555556, dtype=float32)
    """

    def __init__(self, num_labels: int, threshold: float = 0.5, average: Optional[str] = "macro",
                 multidim_average: str = "global", ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(beta=1.0, num_labels=num_labels, threshold=threshold, average=average,
                         multidim_average=multidim_average, ignore_index=ignore_index,
                         validate_args=validate_args, **kwargs)


class FBetaScore:
    """Task façade (reference f_beta.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import FBetaScore
        >>> metric = FBetaScore(task="multiclass", num_classes=3, beta=0.5)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array(0.75, dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        beta: float = 1.0,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: int = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryFBetaScore(beta, threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            assert isinstance(top_k, int)
            return MulticlassFBetaScore(beta, num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelFBetaScore(beta, num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")


class F1Score:
    """Task façade (reference f_beta.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import F1Score
        >>> metric = F1Score(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array(0.75, dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: int = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryF1Score(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            assert isinstance(top_k, int)
            return MulticlassF1Score(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelF1Score(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
