"""Classification module metrics (SURVEY §2.5, reference src/torchmetrics/classification/)."""

from metrics_tpu.classification.accuracy import Accuracy, BinaryAccuracy, MulticlassAccuracy, MultilabelAccuracy
from metrics_tpu.classification.auroc import AUROC, BinaryAUROC, MulticlassAUROC, MultilabelAUROC
from metrics_tpu.classification.average_precision import (
    AveragePrecision,
    BinaryAveragePrecision,
    MulticlassAveragePrecision,
    MultilabelAveragePrecision,
)
from metrics_tpu.classification.calibration_error import (
    BinaryCalibrationError,
    CalibrationError,
    MulticlassCalibrationError,
)
from metrics_tpu.classification.cohen_kappa import BinaryCohenKappa, CohenKappa, MulticlassCohenKappa
from metrics_tpu.classification.dice import Dice
from metrics_tpu.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    ConfusionMatrix,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from metrics_tpu.classification.exact_match import ExactMatch, MulticlassExactMatch, MultilabelExactMatch
from metrics_tpu.classification.f_beta import (
    BinaryF1Score,
    BinaryFBetaScore,
    F1Score,
    FBetaScore,
    MulticlassF1Score,
    MulticlassFBetaScore,
    MultilabelF1Score,
    MultilabelFBetaScore,
)
from metrics_tpu.classification.hinge import BinaryHingeLoss, HingeLoss, MulticlassHingeLoss
from metrics_tpu.classification.hamming import (
    BinaryHammingDistance,
    HammingDistance,
    MulticlassHammingDistance,
    MultilabelHammingDistance,
)
from metrics_tpu.classification.jaccard import (
    BinaryJaccardIndex,
    JaccardIndex,
    MulticlassJaccardIndex,
    MultilabelJaccardIndex,
)
from metrics_tpu.classification.matthews_corrcoef import (
    BinaryMatthewsCorrCoef,
    MatthewsCorrCoef,
    MulticlassMatthewsCorrCoef,
    MultilabelMatthewsCorrCoef,
)
from metrics_tpu.classification.precision_recall import (
    BinaryPrecision,
    BinaryRecall,
    MulticlassPrecision,
    MulticlassRecall,
    MultilabelPrecision,
    MultilabelRecall,
    Precision,
    Recall,
)
from metrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
    PrecisionRecallCurve,
)
from metrics_tpu.classification.ranking import (
    MultilabelCoverageError,
    MultilabelRankingAveragePrecision,
    MultilabelRankingLoss,
)
from metrics_tpu.classification.recall_at_fixed_precision import (
    BinaryRecallAtFixedPrecision,
    MulticlassRecallAtFixedPrecision,
    MultilabelRecallAtFixedPrecision,
    RecallAtFixedPrecision,
)
from metrics_tpu.classification.roc import ROC, BinaryROC, MulticlassROC, MultilabelROC
from metrics_tpu.classification.specificity_at_sensitivity import (
    BinarySpecificityAtSensitivity,
    MulticlassSpecificityAtSensitivity,
    MultilabelSpecificityAtSensitivity,
    SpecificityAtSensitivity,
)
from metrics_tpu.classification.specificity import (
    BinarySpecificity,
    MulticlassSpecificity,
    MultilabelSpecificity,
    Specificity,
)
from metrics_tpu.classification.stat_scores import (
    BinaryStatScores,
    MulticlassStatScores,
    MultilabelStatScores,
    StatScores,
)

__all__ = [
    "AUROC",
    "Accuracy",
    "AveragePrecision",
    "BinaryAUROC",
    "BinaryAccuracy",
    "BinaryAveragePrecision",
    "BinaryCalibrationError",
    "BinaryCohenKappa",
    "BinaryConfusionMatrix",
    "BinaryF1Score",
    "BinaryFBetaScore",
    "BinaryHammingDistance",
    "BinaryHingeLoss",
    "BinaryJaccardIndex",
    "BinaryMatthewsCorrCoef",
    "BinaryPrecision",
    "BinaryPrecisionRecallCurve",
    "BinaryROC",
    "BinaryRecall",
    "BinaryRecallAtFixedPrecision",
    "BinarySpecificity",
    "BinarySpecificityAtSensitivity",
    "BinaryStatScores",
    "CalibrationError",
    "CohenKappa",
    "ConfusionMatrix",
    "Dice",
    "ExactMatch",
    "F1Score",
    "FBetaScore",
    "HammingDistance",
    "HingeLoss",
    "JaccardIndex",
    "MatthewsCorrCoef",
    "MulticlassAUROC",
    "MulticlassAccuracy",
    "MulticlassAveragePrecision",
    "MulticlassCalibrationError",
    "MulticlassCohenKappa",
    "MulticlassConfusionMatrix",
    "MulticlassExactMatch",
    "MulticlassF1Score",
    "MulticlassFBetaScore",
    "MulticlassHammingDistance",
    "MulticlassHingeLoss",
    "MulticlassJaccardIndex",
    "MulticlassMatthewsCorrCoef",
    "MulticlassPrecision",
    "MulticlassPrecisionRecallCurve",
    "MulticlassROC",
    "MulticlassRecall",
    "MulticlassRecallAtFixedPrecision",
    "MulticlassSpecificity",
    "MulticlassSpecificityAtSensitivity",
    "MulticlassStatScores",
    "MultilabelAUROC",
    "MultilabelAccuracy",
    "MultilabelAveragePrecision",
    "MultilabelConfusionMatrix",
    "MultilabelCoverageError",
    "MultilabelExactMatch",
    "MultilabelF1Score",
    "MultilabelFBetaScore",
    "MultilabelHammingDistance",
    "MultilabelJaccardIndex",
    "MultilabelMatthewsCorrCoef",
    "MultilabelPrecision",
    "MultilabelPrecisionRecallCurve",
    "MultilabelROC",
    "MultilabelRankingAveragePrecision",
    "MultilabelRankingLoss",
    "MultilabelRecall",
    "MultilabelRecallAtFixedPrecision",
    "MultilabelSpecificity",
    "MultilabelSpecificityAtSensitivity",
    "MultilabelStatScores",
    "Precision",
    "PrecisionRecallCurve",
    "ROC",
    "Recall",
    "RecallAtFixedPrecision",
    "Specificity",
    "SpecificityAtSensitivity",
    "StatScores",
]
