"""Dice module metric (legacy-style API).

Reference parity: src/torchmetrics/classification/dice.py — legacy StatScores-style
state: fixed-shape sum states for global accumulation (micro → scalars, macro → (C,))
and cat-list states for samplewise modes.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.dice import _dice_compute, _dice_stat_scores_update
from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.data import dim_zero_cat


class Dice(Metric):
    """Dice coefficient.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Dice
        >>> metric = Dice()
        >>> metric.update(jnp.array([0, 1, 1, 0]), jnp.array([0, 1, 0, 0]))
        >>> metric.compute()
        Array(0.75, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        zero_division: float = 0.0,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: Optional[str] = "micro",
        mdmc_average: Optional[str] = "global",
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
        allowed_mdmc_average = ("global", "samplewise", None)
        if mdmc_average not in allowed_mdmc_average:
            raise ValueError(f"The `mdmc_average` has to be one of {allowed_mdmc_average}, got {mdmc_average}.")
        if average in ("macro", "weighted", "none", None) and (num_classes is None or num_classes < 1):
            raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
        if num_classes is not None and ignore_index is not None and not 0 <= ignore_index < num_classes and num_classes > 1:
            raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

        self.zero_division = zero_division
        self.num_classes = num_classes
        self.threshold = threshold
        self.average = average
        self.mdmc_average = mdmc_average
        self.ignore_index = ignore_index
        self.top_k = top_k
        self.multiclass = multiclass
        self.reduce = "macro" if average in ("weighted", "none", None) else average

        # samplewise/samples accumulate per-sample stats → ragged cat states;
        # global micro/macro accumulate fixed-shape sums
        if mdmc_average != "samplewise" and self.reduce != "samples":
            shape = () if self.reduce == "micro" else (num_classes,)
            default, reduce_fx = zero_state(shape, dtype=jnp.int32), "sum"
            self.add_state("tp", default, dist_reduce_fx=reduce_fx)
            self.add_state("fp", default, dist_reduce_fx=reduce_fx)
            self.add_state("tn", default, dist_reduce_fx=reduce_fx)
            self.add_state("fn", default, dist_reduce_fx=reduce_fx)
            self._list_states = False
        else:
            self.add_state("tp", [], dist_reduce_fx="cat")
            self.add_state("fp", [], dist_reduce_fx="cat")
            self.add_state("tn", [], dist_reduce_fx="cat")
            self.add_state("fn", [], dist_reduce_fx="cat")
            self._list_states = True

    def update(self, preds: Array, target: Array) -> None:
        tp, fp, tn, fn = _dice_stat_scores_update(
            preds, target, reduce=self.reduce, mdmc_reduce=self.mdmc_average, num_classes=self.num_classes,
            top_k=self.top_k, threshold=self.threshold, multiclass=self.multiclass, ignore_index=self.ignore_index,
        )
        if self._list_states:
            self.tp.append(jnp.atleast_1d(tp))
            self.fp.append(jnp.atleast_1d(fp))
            self.tn.append(jnp.atleast_1d(tn))
            self.fn.append(jnp.atleast_1d(fn))
        else:
            self.tp = self.tp + tp
            self.fp = self.fp + fp
            self.tn = self.tn + tn
            self.fn = self.fn + fn

    def compute(self) -> Array:
        if self._list_states:
            tp, fp, fn = dim_zero_cat(self.tp), dim_zero_cat(self.fp), dim_zero_cat(self.fn)
        else:
            tp, fp, fn = self.tp, self.fp, self.fn
        return _dice_compute(tp, fp, fn, self.average, self.mdmc_average, self.zero_division)
