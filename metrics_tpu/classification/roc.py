"""ROC module metrics (reference src/torchmetrics/classification/roc.py —
subclasses of the PRC state machinery with a different compute)."""

from __future__ import annotations

from typing import Any, Optional

from metrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from metrics_tpu.functional.classification.roc import (
    _binary_roc_compute,
    _multiclass_roc_compute,
    _multilabel_roc_compute,
)
from metrics_tpu.functional.classification.precision_recall_curve import Thresholds
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.enums import ClassificationTask


class BinaryROC(BinaryPrecisionRecallCurve):
    """ROC curve (fpr, tpr, thresholds).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryROC
        >>> metric = BinaryROC(thresholds=None)
        >>> metric.update(jnp.array([0.11, 0.22, 0.84, 0.73]), jnp.array([0, 1, 1, 1]))
        >>> fpr, tpr, thresholds = metric.compute()
        >>> fpr
        Array([0., 0., 0., 0., 1.], dtype=float32)
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def compute(self):
        state = (dim_zero_cat(self.preds), dim_zero_cat(self.target)) if self.thresholds is None else self.confmat
        return _binary_roc_compute(state, self.thresholds)


class MulticlassROC(MulticlassPrecisionRecallCurve):
    """Multiclass R O C.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassROC
        >>> metric = MulticlassROC(num_classes=3, thresholds=4)
        >>> metric.update(jnp.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]]),
        ...               jnp.array([0, 1, 2, 1]))
        >>> fpr, tpr, thresholds = metric.compute()
        >>> tpr.shape
        (3, 4)
    """
    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def compute(self):
        state = (dim_zero_cat(self.preds), dim_zero_cat(self.target)) if self.thresholds is None else self.confmat
        return _multiclass_roc_compute(state, self.num_classes, self.thresholds)


class MultilabelROC(MultilabelPrecisionRecallCurve):
    """Multilabel R O C.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelROC
        >>> metric = MultilabelROC(num_labels=3, thresholds=4)
        >>> metric.update(jnp.array([[0.9, 0.1, 0.7], [0.2, 0.8, 0.3], [0.6, 0.4, 0.2], [0.1, 0.7, 0.9]]),
        ...               jnp.array([[1, 0, 1], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> fpr, tpr, thresholds = metric.compute()
        >>> fpr.shape
        (3, 4)
    """
    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def compute(self):
        if self.thresholds is None:
            state = (dim_zero_cat(self.preds), dim_zero_cat(self.target), dim_zero_cat(self.mask))
        else:
            state = self.confmat
        return _multilabel_roc_compute(state, self.num_labels, self.thresholds, self.ignore_index)


class ROC:
    """Task façade (reference roc.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import ROC
        >>> metric = ROC(task="binary", thresholds=4)
        >>> metric.update(jnp.array([0.1, 0.6, 0.8, 0.4]), jnp.array([0, 1, 1, 0]))
        >>> fpr, tpr, thresholds = metric.compute()
        >>> tpr
        Array([0. , 0.5, 1. , 1. ], dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryROC(**kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            return MulticlassROC(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelROC(num_labels, **kwargs)
        raise ValueError(f"Not handled value: {task}")
