"""Precision / Recall module metrics.

Reference parity: src/torchmetrics/classification/precision_recall.py.
"""

from __future__ import annotations

from typing import Any, Optional

from jax import Array

from metrics_tpu.classification.stat_scores import BinaryStatScores, MulticlassStatScores, MultilabelStatScores
from metrics_tpu.functional.classification.precision_recall import _precision_recall_reduce
from metrics_tpu.metric import Metric
from metrics_tpu.utils.enums import ClassificationTask


class BinaryPrecision(BinaryStatScores):
    """Binary precision tp/(tp+fp).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryPrecision
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> preds = jnp.array([0, 0, 1, 1, 0, 1])
        >>> metric = BinaryPrecision()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.6666667, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _precision_recall_reduce("precision", tp, fp, tn, fn, average="binary", multidim_average=self.multidim_average)


class MulticlassPrecision(MulticlassStatScores):
    """Multiclass precision, macro-averaged by default.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassPrecision
        >>> target = jnp.array([2, 1, 0, 1])
        >>> preds = jnp.array([2, 1, 0, 1])
        >>> metric = MulticlassPrecision(num_classes=3)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _precision_recall_reduce("precision", tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average)


class MultilabelPrecision(MultilabelStatScores):
    """Multilabel Precision.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelPrecision
        >>> metric = MultilabelPrecision(num_labels=3)
        >>> metric.update(jnp.array([[1, 0, 1], [0, 1, 0], [1, 1, 0], [0, 0, 1]]),
        ...               jnp.array([[1, 0, 0], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> metric.compute()
        Array(0.6666667, dtype=float32)
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _precision_recall_reduce("precision", tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average, multilabel=True)


class BinaryRecall(BinaryStatScores):
    """Binary recall tp/(tp+fn).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryRecall
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> preds = jnp.array([0, 0, 1, 1, 0, 1])
        >>> metric = BinaryRecall()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.6666667, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _precision_recall_reduce("recall", tp, fp, tn, fn, average="binary", multidim_average=self.multidim_average)


class MulticlassRecall(MulticlassStatScores):
    """Multiclass recall, macro-averaged by default.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassRecall
        >>> target = jnp.array([2, 1, 0, 1])
        >>> preds = jnp.array([2, 1, 0, 1])
        >>> metric = MulticlassRecall(num_classes=3)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _precision_recall_reduce("recall", tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average)


class MultilabelRecall(MultilabelStatScores):
    """Multilabel Recall.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelRecall
        >>> metric = MultilabelRecall(num_labels=3)
        >>> metric.update(jnp.array([[1, 0, 1], [0, 1, 0], [1, 1, 0], [0, 0, 1]]),
        ...               jnp.array([[1, 0, 0], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> metric.compute()
        Array(0.8333334, dtype=float32)
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _precision_recall_reduce("recall", tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average, multilabel=True)


class Precision:
    """Task façade (reference precision_recall.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import Precision
        >>> metric = Precision(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array(0.75, dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: int = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryPrecision(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            assert isinstance(top_k, int)
            return MulticlassPrecision(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelPrecision(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")


class Recall:
    """Task façade (reference precision_recall.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import Recall
        >>> metric = Recall(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array(0.75, dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: int = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryRecall(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            assert isinstance(top_k, int)
            return MulticlassRecall(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelRecall(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
