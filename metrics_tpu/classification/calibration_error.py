"""Calibration-error module metrics.

Reference parity: src/torchmetrics/classification/calibration_error.py
(BinaryCalibrationError / MulticlassCalibrationError + ``CalibrationError`` façade).

TPU-native divergence: the reference keeps O(N) ``confidences``/``accuracies`` list
states and bins at compute time; binning into ``n_bins`` uniform buckets commutes with
accumulation, so here the states are the per-bin (acc, conf, count) sums — constant
memory, fixed shape, psum-syncable, and bit-identical results for all three norms.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.calibration_error import (
    _binary_calibration_error_arg_validation,
    _binary_calibration_error_tensor_validation,
    _binary_calibration_error_update,
    _ce_bucketize,
    _ce_compute_from_bins,
    _multiclass_calibration_error_arg_validation,
    _multiclass_calibration_error_tensor_validation,
    _multiclass_calibration_error_update,
)
from metrics_tpu.functional.classification.stat_scores import _ignore_mask, _sigmoid_if_logits, _softmax_if_logits
from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.enums import ClassificationTaskNoMultilabel


class BinaryCalibrationError(Metric):
    """Expected calibration error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryCalibrationError
        >>> metric = BinaryCalibrationError(n_bins=2)
        >>> metric.update(jnp.array([0.9, 0.1, 0.8, 0.3]), jnp.array([1, 0, 1, 1]))
        >>> round(float(metric.compute()), 4)
        0.225
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    acc_bin: Array
    conf_bin: Array
    count_bin: Array

    def __init__(
        self,
        n_bins: int = 15,
        norm: str = "l1",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)
        self.n_bins = n_bins
        self.norm = norm
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("acc_bin", zero_state(n_bins, dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("conf_bin", zero_state(n_bins, dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("count_bin", zero_state(n_bins, dtype=jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _binary_calibration_error_tensor_validation(preds, target, self.ignore_index)
        preds = jnp.asarray(preds).reshape(-1).astype(jnp.float32)
        target = jnp.asarray(target).reshape(-1)
        mask = _ignore_mask(target, self.ignore_index).reshape(-1).astype(jnp.float32)
        target = jnp.where(mask.astype(bool), target, 0)
        preds = _sigmoid_if_logits(preds)
        confidences, accuracies = _binary_calibration_error_update(preds, target)
        acc, conf, count = _ce_bucketize(confidences, accuracies, self.n_bins, weights=mask)
        self.acc_bin = self.acc_bin + acc
        self.conf_bin = self.conf_bin + conf
        self.count_bin = self.count_bin + count

    def compute(self) -> Array:
        return _ce_compute_from_bins(self.acc_bin, self.conf_bin, self.count_bin, self.norm)


class MulticlassCalibrationError(Metric):
    """Multiclass Calibration Error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassCalibrationError
        >>> metric = MulticlassCalibrationError(num_classes=3)
        >>> metric.update(jnp.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]]),
        ...               jnp.array([0, 1, 2, 1]))
        >>> metric.compute()
        Array(0.4, dtype=float32)
    """
    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    acc_bin: Array
    conf_bin: Array
    count_bin: Array

    def __init__(
        self,
        num_classes: int,
        n_bins: int = 15,
        norm: str = "l1",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_calibration_error_arg_validation(num_classes, n_bins, norm, ignore_index)
        self.num_classes = num_classes
        self.n_bins = n_bins
        self.norm = norm
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("acc_bin", zero_state(n_bins, dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("conf_bin", zero_state(n_bins, dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("count_bin", zero_state(n_bins, dtype=jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multiclass_calibration_error_tensor_validation(preds, target, self.num_classes, self.ignore_index)
        preds = jnp.moveaxis(jnp.asarray(preds), 1, -1).reshape(-1, self.num_classes).astype(jnp.float32)
        target = jnp.asarray(target).reshape(-1)
        mask = _ignore_mask(target, self.ignore_index).astype(jnp.float32)
        target = jnp.where(mask.astype(bool), target, 0)
        preds = _softmax_if_logits(preds, axis=-1)
        confidences, accuracies = _multiclass_calibration_error_update(preds, target)
        acc, conf, count = _ce_bucketize(confidences, accuracies, self.n_bins, weights=mask)
        self.acc_bin = self.acc_bin + acc
        self.conf_bin = self.conf_bin + conf
        self.count_bin = self.count_bin + count

    def compute(self) -> Array:
        return _ce_compute_from_bins(self.acc_bin, self.conf_bin, self.count_bin, self.norm)


class CalibrationError:
    """Task façade (reference calibration_error.py ``CalibrationError.__new__``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import CalibrationError
        >>> metric = CalibrationError(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]]),
        ...               jnp.array([0, 1, 2, 1]))
        >>> metric.compute()
        Array(0.4, dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        n_bins: int = 15,
        norm: str = "l1",
        num_classes: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTaskNoMultilabel.from_str_or_raise(task)
        kwargs.update({"n_bins": n_bins, "norm": norm, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTaskNoMultilabel.BINARY:
            return BinaryCalibrationError(**kwargs)
        if task == ClassificationTaskNoMultilabel.MULTICLASS:
            assert isinstance(num_classes, int)
            return MulticlassCalibrationError(num_classes, **kwargs)
        raise ValueError(f"Not handled value: {task}")
