"""Specificity module metrics (reference src/torchmetrics/classification/specificity.py)."""

from __future__ import annotations

from typing import Any, Optional

from jax import Array

from metrics_tpu.classification.stat_scores import BinaryStatScores, MulticlassStatScores, MultilabelStatScores
from metrics_tpu.functional.classification.specificity import _specificity_reduce
from metrics_tpu.metric import Metric
from metrics_tpu.utils.enums import ClassificationTask


class BinarySpecificity(BinaryStatScores):
    """Binary specificity tn/(tn+fp).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinarySpecificity
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> preds = jnp.array([0, 0, 1, 1, 0, 1])
        >>> metric = BinarySpecificity()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.6666667, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _specificity_reduce(tp, fp, tn, fn, average="binary", multidim_average=self.multidim_average)


class MulticlassSpecificity(MulticlassStatScores):
    """Multiclass Specificity.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassSpecificity
        >>> metric = MulticlassSpecificity(num_classes=3)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array(0.88888896, dtype=float32)
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _specificity_reduce(tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average)


class MultilabelSpecificity(MultilabelStatScores):
    """Multilabel Specificity.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelSpecificity
        >>> metric = MultilabelSpecificity(num_labels=3)
        >>> metric.update(jnp.array([[1, 0, 1], [0, 1, 0], [1, 1, 0], [0, 0, 1]]),
        ...               jnp.array([[1, 0, 0], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> metric.compute()
        Array(0.7222222, dtype=float32)
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _specificity_reduce(tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average, multilabel=True)


class Specificity:
    """Task façade (reference specificity.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import Specificity
        >>> metric = Specificity(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array(0.875, dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: int = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinarySpecificity(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            assert isinstance(top_k, int)
            return MulticlassSpecificity(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelSpecificity(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
