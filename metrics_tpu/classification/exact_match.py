"""Exact match module metrics (reference src/torchmetrics/classification/exact_match.py)."""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.exact_match import _exact_match_reduce
from metrics_tpu.functional.classification.stat_scores import (
    _ignore_mask,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
)
from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.enums import ClassificationTask


class _AbstractExactMatch(Metric):
    def _create_state(self, multidim_average: str) -> None:
        if multidim_average == "samplewise":
            self.add_state("correct", [], dist_reduce_fx="cat")
            self.add_state("total", zero_state((), dtype=jnp.float32), dist_reduce_fx="sum")
        else:
            self.add_state("correct", zero_state((), dtype=jnp.int32), dist_reduce_fx="sum")
            self.add_state("total", zero_state((), dtype=jnp.float32), dist_reduce_fx="sum")

    def _update_state(self, correct: Array, total: Array) -> None:
        if isinstance(self.correct, list):
            self.correct.append(correct)
        else:
            self.correct = self.correct + jnp.sum(correct)
        self.total = self.total + total

    def compute(self) -> Array:
        correct = dim_zero_cat(self.correct) if isinstance(self.correct, list) else self.correct
        return _exact_match_reduce(correct, self.total, self.multidim_average)


class MulticlassExactMatch(_AbstractExactMatch):
    """Samplewise all-labels-correct indicator, averaged.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassExactMatch
        >>> target = jnp.array([[0, 1], [2, 1]])
        >>> preds = jnp.array([[0, 1], [2, 0]])
        >>> metric = MulticlassExactMatch(num_classes=3)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.5, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_classes: int,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_stat_scores_arg_validation(num_classes, top_k=1, average=None, multidim_average=multidim_average, ignore_index=ignore_index)
        self.num_classes = num_classes
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(multidim_average)

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multiclass_stat_scores_tensor_validation(preds, target, self.num_classes, self.multidim_average, self.ignore_index)
        preds, target = _multiclass_stat_scores_format(preds, target, top_k=1)
        mask = _ignore_mask(target, self.ignore_index)
        correct = jnp.all(jnp.where(mask, preds == target, True), axis=1).astype(jnp.int32)
        self._update_state(correct, jnp.asarray(correct.shape[0], dtype=jnp.float32))


class MultilabelExactMatch(_AbstractExactMatch):
    """Multilabel Exact Match.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelExactMatch
        >>> metric = MultilabelExactMatch(num_labels=3)
        >>> metric.update(jnp.array([[1, 0, 1], [0, 1, 0], [1, 1, 0], [0, 0, 1]]),
        ...               jnp.array([[1, 0, 0], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> metric.compute()
        Array(0.25, dtype=float32)
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_stat_scores_arg_validation(num_labels, threshold, average=None, multidim_average=multidim_average, ignore_index=ignore_index)
        self.num_labels = num_labels
        self.threshold = threshold
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(multidim_average)

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multilabel_stat_scores_tensor_validation(preds, target, self.num_labels, self.multidim_average, self.ignore_index)
        squeeze_x = jnp.asarray(preds).ndim == 2
        preds, target, mask = _multilabel_stat_scores_format(preds, target, self.num_labels, self.threshold, self.ignore_index)
        correct = jnp.all(jnp.where(mask, preds == target, True), axis=1).astype(jnp.int32)
        if squeeze_x:
            correct = correct.squeeze(-1)
        self._update_state(correct, jnp.asarray(correct.size, dtype=jnp.float32))


class ExactMatch:
    """Task façade (reference exact_match.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import ExactMatch
        >>> metric = ExactMatch(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([[0, 2], [1, 1]]), jnp.array([[0, 2], [1, 0]]))
        >>> metric.compute()
        Array(0.5, dtype=float32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            return MulticlassExactMatch(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelExactMatch(num_labels, threshold, **kwargs)
        raise ValueError(f"Expected argument `task` to either be 'multiclass' or 'multilabel' but got {task}")
