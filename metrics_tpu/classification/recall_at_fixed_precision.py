"""Recall-at-fixed-precision module metrics.

Reference parity: src/torchmetrics/classification/recall_at_fixed_precision.py —
each variant subclasses its precision-recall-curve metric (inheriting the two state
regimes) and overrides ``compute``.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from jax import Array

from metrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from metrics_tpu.functional.classification.precision_recall_curve import Thresholds
from metrics_tpu.functional.classification.recall_at_fixed_precision import (
    _binary_recall_at_fixed_precision_arg_validation,
    _binary_recall_at_fixed_precision_compute,
    _multiclass_recall_at_fixed_precision_arg_compute,
    _multiclass_recall_at_fixed_precision_arg_validation,
    _multilabel_recall_at_fixed_precision_arg_compute,
    _multilabel_recall_at_fixed_precision_arg_validation,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.enums import ClassificationTask


class BinaryRecallAtFixedPrecision(BinaryPrecisionRecallCurve):
    """Best recall with precision >= the constraint, plus the threshold.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryRecallAtFixedPrecision
        >>> probs = jnp.array([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> metric = BinaryRecallAtFixedPrecision(min_precision=0.5)
        >>> metric.update(probs, target)
        >>> [round(float(v), 4) for v in metric.compute()]
        [1.0, 0.22]
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        min_precision: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(thresholds=thresholds, ignore_index=ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _binary_recall_at_fixed_precision_arg_validation(min_precision, thresholds, ignore_index)
        self.validate_args = validate_args
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:
        state = (dim_zero_cat(self.preds), dim_zero_cat(self.target)) if self.thresholds is None else self.confmat
        return _binary_recall_at_fixed_precision_compute(state, self.thresholds, self.min_precision)


class MulticlassRecallAtFixedPrecision(MulticlassPrecisionRecallCurve):
    """Multiclass Recall At Fixed Precision.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassRecallAtFixedPrecision
        >>> metric = MulticlassRecallAtFixedPrecision(num_classes=3, min_precision=0.5, thresholds=4)
        >>> metric.update(jnp.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]]),
        ...               jnp.array([0, 1, 2, 1]))
        >>> metric.compute()
        (Array([1., 1., 1.], dtype=float32), Array([0.6666667 , 0.33333334, 0.6666667 ], dtype=float32))
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_classes: int,
        min_precision: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes=num_classes, thresholds=thresholds, ignore_index=ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _multiclass_recall_at_fixed_precision_arg_validation(num_classes, min_precision, thresholds, ignore_index)
        self.validate_args = validate_args
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:
        state = (dim_zero_cat(self.preds), dim_zero_cat(self.target)) if self.thresholds is None else self.confmat
        return _multiclass_recall_at_fixed_precision_arg_compute(state, self.num_classes, self.thresholds, self.min_precision)


class MultilabelRecallAtFixedPrecision(MultilabelPrecisionRecallCurve):
    """Multilabel Recall At Fixed Precision.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelRecallAtFixedPrecision
        >>> metric = MultilabelRecallAtFixedPrecision(num_labels=3, min_precision=0.5, thresholds=4)
        >>> metric.update(jnp.array([[0.9, 0.1, 0.7], [0.2, 0.8, 0.3], [0.6, 0.4, 0.2], [0.1, 0.7, 0.9]]),
        ...               jnp.array([[1, 0, 1], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> metric.compute()
        (Array([1., 1., 1.], dtype=float32), Array([0.33333334, 0.6666667 , 0.6666667 ], dtype=float32))
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_labels: int,
        min_precision: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _multilabel_recall_at_fixed_precision_arg_validation(num_labels, min_precision, thresholds, ignore_index)
        self.validate_args = validate_args
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:
        state = self._curve_state()
        return _multilabel_recall_at_fixed_precision_arg_compute(
            state, self.num_labels, self.thresholds, self.ignore_index, self.min_precision
        )


class RecallAtFixedPrecision:
    """Task façade (reference recall_at_fixed_precision.py ``__new__``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import RecallAtFixedPrecision
        >>> metric = RecallAtFixedPrecision(task="binary", min_precision=0.5, thresholds=4)
        >>> metric.update(jnp.array([0.1, 0.6, 0.8, 0.4]), jnp.array([0, 1, 1, 0]))
        >>> metric.compute()
        (Array(1., dtype=float32), Array(0.33333334, dtype=float32))
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        min_precision: float,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        if task == ClassificationTask.BINARY:
            return BinaryRecallAtFixedPrecision(min_precision, thresholds, ignore_index, validate_args, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            return MulticlassRecallAtFixedPrecision(num_classes, min_precision, thresholds, ignore_index, validate_args, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelRecallAtFixedPrecision(num_labels, min_precision, thresholds, ignore_index, validate_args, **kwargs)
        raise ValueError(f"Not handled value: {task}")
