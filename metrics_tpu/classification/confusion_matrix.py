"""Confusion-matrix module metrics (reference src/torchmetrics/classification/confusion_matrix.py)."""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_arg_validation,
    _binary_confusion_matrix_update,
    _confusion_matrix_reduce,
    _multiclass_confusion_matrix_update,
    _multilabel_confusion_matrix_update,
)
from metrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
)
from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.enums import ClassificationTask


class BinaryConfusionMatrix(Metric):
    """2x2 confusion matrix from thresholded probabilities or labels.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import BinaryConfusionMatrix
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> probs = jnp.array([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> metric = BinaryConfusionMatrix()
        >>> metric.update(probs, target)
        >>> metric.compute()
        Array([[2, 1],
               [1, 2]], dtype=int32)
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        normalize: Optional[str] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize)
        self.threshold = threshold
        self.ignore_index = ignore_index
        self.normalize = normalize
        self.validate_args = validate_args
        self.add_state("confmat", zero_state((2, 2), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _binary_stat_scores_tensor_validation(preds, target, "global", self.ignore_index)
        preds, target, mask = _binary_stat_scores_format(preds, target, self.threshold, self.ignore_index)
        self.confmat = self.confmat + _binary_confusion_matrix_update(preds, target, mask)

    def compute(self) -> Array:
        return _confusion_matrix_reduce(self.confmat, self.normalize)


class MulticlassConfusionMatrix(Metric):
    """(C, C) confusion matrix via one-hot matmul accumulation.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MulticlassConfusionMatrix
        >>> target = jnp.array([2, 1, 0, 1])
        >>> preds = jnp.array([2, 1, 0, 1])
        >>> metric = MulticlassConfusionMatrix(num_classes=3)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array([[1, 0, 0],
               [0, 2, 0],
               [0, 0, 1]], dtype=int32)
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        num_classes: int,
        ignore_index: Optional[int] = None,
        normalize: Optional[str] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.ignore_index = ignore_index
        self.normalize = normalize
        self.validate_args = validate_args
        self.add_state("confmat", zero_state((num_classes, num_classes), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multiclass_stat_scores_tensor_validation(preds, target, self.num_classes, "global", self.ignore_index)
        preds, target = _multiclass_stat_scores_format(preds, target, top_k=1)
        self.confmat = self.confmat + _multiclass_confusion_matrix_update(preds, target, self.num_classes, self.ignore_index)

    def compute(self) -> Array:
        return _confusion_matrix_reduce(self.confmat, self.normalize)


class MultilabelConfusionMatrix(Metric):
    """Multilabel Confusion Matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import MultilabelConfusionMatrix
        >>> metric = MultilabelConfusionMatrix(num_labels=3)
        >>> metric.update(jnp.array([[1, 0, 1], [0, 1, 0], [1, 1, 0], [0, 0, 1]]),
        ...               jnp.array([[1, 0, 0], [0, 1, 0], [1, 0, 0], [0, 1, 1]]))
        >>> metric.compute()
        Array([[[2, 0],
                [0, 2]],
        <BLANKLINE>
               [[1, 1],
                [1, 1]],
        <BLANKLINE>
               [[2, 1],
                [0, 1]]], dtype=int32)
    """
    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        normalize: Optional[str] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_labels = num_labels
        self.threshold = threshold
        self.ignore_index = ignore_index
        self.normalize = normalize
        self.validate_args = validate_args
        self.add_state("confmat", zero_state((num_labels, 2, 2), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multilabel_stat_scores_tensor_validation(preds, target, self.num_labels, "global", self.ignore_index)
        preds, target, mask = _multilabel_stat_scores_format(preds, target, self.num_labels, self.threshold, self.ignore_index)
        self.confmat = self.confmat + _multilabel_confusion_matrix_update(preds, target, mask, self.num_labels)

    def compute(self) -> Array:
        return _confusion_matrix_reduce(self.confmat, self.normalize)


class ConfusionMatrix:
    """Task façade (reference confusion_matrix.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.classification import ConfusionMatrix
        >>> metric = ConfusionMatrix(task="multiclass", num_classes=3)
        >>> metric.update(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        >>> metric.compute()
        Array([[1, 0, 0],
               [0, 1, 1],
               [0, 0, 1]], dtype=int32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        normalize: Optional[str] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str_or_raise(task)
        kwargs.update({"normalize": normalize, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryConfusionMatrix(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            assert isinstance(num_classes, int)
            return MulticlassConfusionMatrix(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            assert isinstance(num_labels, int)
            return MultilabelConfusionMatrix(num_labels, threshold, **kwargs)
        raise ValueError(f"Not handled value: {task}")
