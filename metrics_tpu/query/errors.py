"""Query-plane error types."""

from __future__ import annotations

from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["NoLivePartitionsError", "PartialResultError", "RollupUnsupported"]


class RollupUnsupported(MetricsTPUUserError):
    """The metric carries a state a rollup cannot fold.

    A partition rollup is a FIXED-SIZE mergeable summary — one state pytree
    the shape of a single tenant's, standing in for all of them. States with
    ``dist_reduce_fx`` of ``'cat'`` or ``None`` grow with the stream (raw
    sample lists, per-example arrays), so folding a million tenants' worth
    would reconstruct the stream, not summarize it. Use a sketch-family
    metric (DDSketch / HLL / CMS) or a reducible scalar state instead.
    """


class NoLivePartitionsError(MetricsTPUUserError):
    """Every partition was unreachable: there is no subset to degrade to.

    A global query degrades to a *named* live subset when SOME partitions
    are missing; with none contributing, any value would be fabricated.
    The message names every partition and why it failed.
    """


class PartialResultError(MetricsTPUUserError):
    """Raised instead of degrading when ``require_full=True`` and at least
    one partition is missing — for callers whose answer is only meaningful
    over the whole fleet. The missing partitions are named."""
