"""Watermark-keyed result cache — validity by comparison, not by clock.

A cached global state is the merge of partition rollups, each stamped with
the ``(epoch, seq)`` WAL watermark its slab snapshot was captured at. That
stamp makes validity EXACT, not heuristic: the cached merge is the true
global answer for as long as no contributing partition has journaled
anything past its watermark, and the instant one has, the stamp says so.
Revalidation is therefore a per-partition watermark *probe* (two ints over
the read path, servable by a follower) and an equality-shaped compare — no
slab fold, no merge tree, no TTL guessing.

The compare is generation-safe by construction: seq numbers are only
comparable within one primary lineage, and the epoch component changes on
every failover, so a promoted partition invalidates every cached result it
contributed to even if its new lineage happens to reuse seq numbers —
cached results can never mix watermark generations.

A probe seq BEHIND the cached stamp (same epoch) stays valid: it means the
probe landed on a replica lagging the one that served the rollup, and the
cached state is *fresher* evidence than the prober's own slab — the cache's
staleness stays bounded by the probing replica's own bounded-staleness
contract, never looser.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, Hashable, Optional, Tuple

from metrics_tpu.query.report import QueryReport

__all__ = ["CachedGlobal", "WatermarkCache", "watermark_compatible"]


def watermark_compatible(cached: Tuple[int, int], probe: Tuple[int, int]) -> bool:
    """Is a cached stamp still valid against a freshly probed watermark?

    Valid iff the lineage is the SAME epoch and the probed seq has not
    advanced past the cached one. Any epoch difference — even "older" —
    invalidates: epochs are lineage identities, not magnitudes to order by.

    A cached stamp with ``seq < 0`` never validates: ``-1`` means the serving
    engine had journaled nothing (or has no durable plane), and for an
    un-journaled engine the stamp would never advance — "never changes" would
    silently mean "never invalidates" over state that does change.
    """
    return cached[1] >= 0 and probe[0] == cached[0] and probe[1] <= cached[1]


@dataclass(frozen=True)
class CachedGlobal:
    """One cached global merge: the state, its per-partition stamps, and the
    report describing the merge that produced it."""

    state: Dict[str, Any]
    watermarks: Dict[str, Tuple[int, int]]  # contributing partition -> stamp
    missing: Tuple[str, ...]  # partitions absent when the merge ran
    report: QueryReport
    tenants: int


class WatermarkCache:
    """Small thread-safe LRU of :class:`CachedGlobal` entries.

    Keys are whatever the caller derives from (metric fingerprint, window) —
    the cache itself only stores and evicts; validity is the caller's
    watermark compare, because validity needs fresh probes the cache cannot
    take."""

    def __init__(self, capacity: int = 32) -> None:
        if int(capacity) < 1:
            raise ValueError(f"`capacity` must be >= 1, got {capacity}")
        self._capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Hashable, CachedGlobal]" = OrderedDict()

    def get(self, key: Hashable) -> Optional[CachedGlobal]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
            return entry

    def put(self, key: Hashable, entry: CachedGlobal) -> None:
        with self._lock:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self._capacity:
                self._entries.popitem(last=False)

    def invalidate(self, key: Optional[Hashable] = None) -> None:
        """Drop one entry (or all of them) — the ops escape hatch."""
        with self._lock:
            if key is None:
                self._entries.clear()
            else:
                self._entries.pop(key, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
