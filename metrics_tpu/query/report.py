"""Query reports — where a global answer came from, and what it is missing.

The honesty contract in one object: every partition that contributed is
listed with the node that served it, whether that node was a follower, its
``(epoch, seq)`` watermark, and its staleness evidence; every partition that
did NOT contribute is *named* in ``partitions_missing`` with the refusal
that excluded it. A degraded answer is therefore an agreed, named subset —
never a silent undercount.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, Optional, Tuple

__all__ = ["GlobalResult", "PartitionReport", "QueryReport"]


@dataclass(frozen=True)
class PartitionReport:
    """One partition's contribution to (or absence from) a global answer."""

    partition: str
    node: str = ""
    follower: bool = False
    watermark: Optional[Tuple[int, int]] = None
    tenants: int = 0
    staleness_seqs: Optional[int] = None
    staleness_s: Optional[float] = None
    error: str = ""  # why it is missing ("" when it contributed)

    @property
    def missing(self) -> bool:
        return self.watermark is None


@dataclass(frozen=True)
class QueryReport:
    """Provenance of one global query answer."""

    op: str
    partitions: Tuple[PartitionReport, ...] = ()
    partitions_missing: Tuple[str, ...] = ()
    watermarks: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    cache_hit: bool = False
    merge_hops: int = 0
    tenants: int = 0

    @property
    def degraded(self) -> bool:
        """True when the answer covers a strict live subset of the fleet."""
        return bool(self.partitions_missing)

    @property
    def follower_served(self) -> bool:
        """True when NO contributing rollup touched a write leader."""
        served = [p for p in self.partitions if not p.missing]
        return bool(served) and all(p.follower for p in served)


@dataclass(frozen=True)
class GlobalResult:
    """``value`` + ``report``; unpacks like a pair for ergonomic call sites:
    ``value, report = gq.quantile(metric, 0.99)``."""

    value: Any
    report: QueryReport

    def __iter__(self) -> Iterator[Any]:
        yield self.value
        yield self.report
