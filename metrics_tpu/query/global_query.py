"""GlobalQuery — fleet-wide analytics over every partition, as one read path.

The scatter loop this plane replaces asked every partition leader for every
tenant and re-aggregated client-side. GlobalQuery instead asks each
partition for ONE rollup (all local tenants pre-folded, servable by a
follower), merges the rollups through a deterministic multi-hop tree, and
stamps the result with every contributor's ``(epoch, seq)`` WAL watermark:

- a partition that cannot serve (headless past the retry budget, every
  replica refusing its staleness bound) is NAMED in
  ``QueryReport.partitions_missing`` — the answer degrades to an agreed
  live subset, never a silent undercount and never a deadlock;
- repeat queries revalidate by watermark compare (two ints per partition,
  follower-servable) and reuse the cached merge until some partition's
  journal actually advances — see :mod:`metrics_tpu.query.cache` for the
  validity argument;
- with ``prefer="replica"`` (the default) both rollups and watermark probes
  are served by followers under the bounded-staleness contract, so a
  dashboard read storm never touches a write leader
  (``metrics_tpu_query_leader_reads_total`` counts the exceptions).

The cache stores the merged global STATE, not a single scalar: one cached
merge answers ``quantile(m, 0.5)``, ``quantile(m, 0.99)`` and
``cardinality(m)`` alike, because the expensive part — rollup folds and the
merge tree — is identical for all of them.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any, Dict, Hashable, List, Optional, Tuple, Union

import jax.numpy as jnp

from metrics_tpu.cluster.errors import NoLeaderError
from metrics_tpu.engine.runtime import EngineClosed, EngineQuarantined
from metrics_tpu.obs import instrument as _obs
from metrics_tpu.query.cache import CachedGlobal, WatermarkCache, watermark_compatible
from metrics_tpu.query.errors import NoLivePartitionsError, PartialResultError
from metrics_tpu.query.report import GlobalResult, PartitionReport, QueryReport
from metrics_tpu.query.rollup import PartitionRollup
from metrics_tpu.query.tree import merge_tree
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["GlobalQuery"]

# "this partition cannot contribute right now": routing exhausted every node
# (headless, staleness-refused everywhere, dead handles) or the only engine
# is wedged/closed. Anything else — RollupUnsupported, a caller error — is a
# bug to surface, not a partition to degrade away.
_MISSING = (NoLeaderError, EngineQuarantined, EngineClosed)


def _metric_key(metric: Any) -> Tuple[Any, ...]:
    """State-shape fingerprint: two metrics whose states are interchangeable
    (same names, shapes, dtypes) share cached merges — the cached state came
    from the ENGINES, the metric argument only interprets it."""
    init = metric.init_state()
    leaves: List[Tuple[Any, ...]] = []
    for name in sorted(init):
        v = init[name]
        if isinstance(v, list):
            leaves.append((name, "list"))
        else:
            arr = jnp.asarray(v)
            leaves.append((name, tuple(arr.shape), str(arr.dtype)))
    return (type(metric).__name__, tuple(leaves))


class GlobalQuery:
    """Fleet-wide reads over a :class:`~metrics_tpu.part.PartitionedClient`.

    Args:
        client: the partitioned client (its per-partition routers serve the
            rollup and watermark reads with the routing contract's redirect +
            backoff ladder).
        prefer: ``"replica"`` (default) serves rollups/probes from followers
            under bounded staleness; ``"leader"`` reads the writable truth.
        fan_in: merge-tree arity (see :func:`metrics_tpu.query.tree.merge_tree`).
        cache: a shared :class:`WatermarkCache` (one is built when omitted).
        cache_capacity: LRU capacity of the built-in cache.
        require_full: raise :class:`PartialResultError` instead of degrading
            to a named subset when any partition is missing.
        probe_retries: router retry budget for watermark probes (kept small:
            a failed probe falls back to a full re-merge, which is correct —
            just slower — so the hit path should not inherit the write
            path's full patience).
    """

    def __init__(
        self,
        client: Any,
        *,
        prefer: str = "replica",
        fan_in: int = 4,
        cache: Optional[WatermarkCache] = None,
        cache_capacity: int = 32,
        require_full: bool = False,
        probe_retries: int = 1,
    ) -> None:
        if prefer not in ("leader", "replica"):
            raise ValueError(f"prefer must be 'leader' or 'replica', got {prefer!r}")
        self._client = client
        self._prefer = prefer
        self._fan_in = int(fan_in)
        self._cache = cache if cache is not None else WatermarkCache(cache_capacity)
        self._require_full = bool(require_full)
        self._probe_retries = int(probe_retries)

    # ------------------------------------------------------------------ public ops

    def compute(self, metric: Any, *, window: bool = False) -> GlobalResult:
        """Global value of any reducible-state metric (all tenants merged)."""
        state, report = self._global_state(metric, "compute", window)
        return GlobalResult(metric.compute_from(state), report)

    def quantile(self, metric: Any, q: Union[float, Any], *, window: bool = False) -> GlobalResult:
        """Global quantile(s) ``q`` from a merged DDSketch state."""
        if not hasattr(metric, "quantile_from"):
            raise MetricsTPUUserError(
                f"quantile() needs a quantile sketch (a metric with `quantile_from`), "
                f"got {type(metric).__name__}"
            )
        state, report = self._global_state(metric, "quantile", window)
        return GlobalResult(metric.quantile_from(state, q), report)

    def cardinality(self, metric: Any, *, window: bool = False) -> GlobalResult:
        """Global distinct count from a merged HLL state."""
        state, report = self._global_state(metric, "cardinality", window)
        return GlobalResult(metric.compute_from(state), report)

    def top_k(self, metric: Any, k: Optional[int] = None, *, window: bool = False) -> GlobalResult:
        """Global heavy hitters from a merged CMS + ledger state."""
        if not hasattr(metric, "topk_from"):
            raise MetricsTPUUserError(
                f"top_k() needs a heavy-hitters sketch (a metric with `topk_from`), "
                f"got {type(metric).__name__}"
            )
        state, report = self._global_state(metric, "top_k", window)
        return GlobalResult(metric.topk_from(state, k), report)

    @property
    def cache(self) -> WatermarkCache:
        return self._cache

    # ------------------------------------------------------------------ machinery

    def _partition_ids(self) -> List[int]:
        return list(range(self._client.pmap.partitions))

    def _global_state(
        self, metric: Any, op: str, window: bool
    ) -> Tuple[Dict[str, Any], QueryReport]:
        key: Hashable = (bool(window), _metric_key(metric))
        cached = self._cache.get(key)
        if cached is not None and self._revalidate(cached, op):
            _obs.record_query(op, cached=True)
            return cached.state, replace(cached.report, op=op, cache_hit=True)
        return self._merge(metric, op, window, key)

    def _revalidate(self, cached: CachedGlobal, op: str) -> bool:
        """Watermark compare, not a re-merge: True iff every contributing
        partition's probed stamp is compatible AND no previously-missing
        partition has come back (a returned partition must be re-admitted
        into the merge, so its recovery is a miss by design)."""
        names = {self._client.pmap.name_of(pid): pid for pid in self._partition_ids()}
        for pname, stamp in cached.watermarks.items():
            pid = names.get(pname)
            if pid is None:
                return False  # the partition map itself changed shape
            try:
                wm, _node, is_leader = self._client.wal_watermark(
                    pid, prefer=self._prefer, retries=self._probe_retries
                )
            except _MISSING:
                return False  # can't vouch for the stamp: re-merge (and name it)
            if is_leader:
                _obs.record_query_leader_read(op)
            if not watermark_compatible(stamp, wm):
                return False
        for pname in cached.missing:
            pid = names.get(pname)
            if pid is None:
                return False
            try:
                _wm, _node, is_leader = self._client.wal_watermark(
                    pid, prefer=self._prefer, retries=0
                )
            except _MISSING:
                continue  # still gone: the cached subset is still the live one
            if is_leader:
                _obs.record_query_leader_read(op)
            return False  # it came back — re-merge to re-admit it
        return True

    def _merge(
        self, metric: Any, op: str, window: bool, key: Hashable
    ) -> Tuple[Dict[str, Any], QueryReport]:
        rollups: List[PartitionRollup] = []
        part_reports: List[PartitionReport] = []
        missing: List[str] = []
        for pid in self._partition_ids():
            pname = self._client.pmap.name_of(pid)
            try:
                ru, node, is_leader = self._client.rollup(
                    pid, prefer=self._prefer, window=window
                )
            except _MISSING as exc:
                missing.append(pname)
                part_reports.append(
                    PartitionReport(partition=pname, error=f"{type(exc).__name__}: {exc}")
                )
                _obs.record_query_partition_missing(pname)
                continue
            if is_leader:
                _obs.record_query_leader_read(op)
            rollups.append(ru)
            part_reports.append(
                PartitionReport(
                    partition=pname,
                    node=node,
                    follower=ru.follower,
                    watermark=ru.watermark,
                    tenants=ru.tenants,
                    staleness_seqs=ru.staleness_seqs,
                    staleness_s=ru.staleness_s,
                )
            )
        if not rollups:
            raise NoLivePartitionsError(
                "global query could not reach ANY partition — nothing to degrade to. "
                + "; ".join(f"{r.partition}: {r.error}" for r in part_reports)
            )
        if missing and self._require_full:
            raise PartialResultError(
                f"global query is missing partitions {tuple(missing)!r} and "
                "require_full=True"
            )
        # empty partitions are excluded from the MERGE, not the report: their
        # state is the reduction identity, but callable reductions (topk_merge)
        # canonicalize representation on contact, so folding identities in
        # would break bit-identity with the centralized oracle for singleton
        # merges. Their watermarks still gate the cache — a tenant landing on
        # an empty partition advances its seq and invalidates.
        state, hops = merge_tree(
            metric, [r.state for r in rollups if r.tenants > 0], fan_in=self._fan_in
        )
        tenants = sum(r.tenants for r in rollups)
        report = QueryReport(
            op=op,
            partitions=tuple(part_reports),
            partitions_missing=tuple(missing),
            watermarks={r.partition: r.watermark for r in rollups},
            cache_hit=False,
            merge_hops=hops,
            tenants=tenants,
        )
        self._cache.put(
            key,
            CachedGlobal(
                state=state,
                watermarks=dict(report.watermarks),
                missing=tuple(missing),
                report=report,
                tenants=tenants,
            ),
        )
        _obs.record_query(op, cached=False)
        return state, report
