"""Global query plane — fleet-wide analytics as a first-class read path.

One :class:`GlobalQuery` over a partitioned client answers "what is the p99
across ALL tenants" with P rollup reads instead of a million per-tenant
scatters: each partition folds its local tenants into one mergeable state
(:mod:`~metrics_tpu.query.rollup`), the rollups reduce through a
deterministic merge tree (:mod:`~metrics_tpu.query.tree`), and results are
cached under per-partition WAL watermarks (:mod:`~metrics_tpu.query.cache`)
so repeat queries revalidate with a seq compare instead of a re-merge.
See docs/source/queries.md.
"""

from metrics_tpu.query.cache import CachedGlobal, WatermarkCache, watermark_compatible
from metrics_tpu.query.errors import (
    NoLivePartitionsError,
    PartialResultError,
    RollupUnsupported,
)
from metrics_tpu.query.global_query import GlobalQuery
from metrics_tpu.query.report import GlobalResult, PartitionReport, QueryReport
from metrics_tpu.query.rollup import (
    PartitionRollup,
    fold_slab,
    fold_states,
    merge_folds,
)
from metrics_tpu.query.tree import merge_tree

__all__ = [
    "CachedGlobal",
    "GlobalQuery",
    "GlobalResult",
    "NoLivePartitionsError",
    "PartialResultError",
    "PartitionReport",
    "PartitionRollup",
    "QueryReport",
    "RollupUnsupported",
    "WatermarkCache",
    "fold_slab",
    "fold_states",
    "merge_folds",
    "merge_tree",
    "watermark_compatible",
]
