"""Partition rollups — every local tenant folded into ONE mergeable state.

The fold is the vectorised analogue of repeated
:meth:`~metrics_tpu.metric.Metric.merge_states` over all tenants, computed as
one slab-axis reduction per leaf instead of K-1 pairwise tree ops:

- ``sum`` states reduce with ``jnp.sum`` over the tenant axis — bit-identical
  to any pairwise merge order for the integer states every sketch family
  carries (DDSketch buckets, HLL registers, CMS tables are all int32);
- ``min`` / ``max`` states reduce elementwise — exact in any order;
- ``mean`` states reduce as one ``_update_count``-weighted sum (the same
  formula ``merge_states`` applies pairwise; for floating-point states the
  single weighted sum and a nested pairwise merge can differ in rounding —
  both are within each other's accumulation error);
- callable reductions take the WHOLE ``(K, ...)`` stack in one call — the
  :func:`~metrics_tpu.sketch.kernels.topk_merge` contract, whose merge is
  commutative bit-for-bit and exactly associative while the candidate union
  fits the ledger.

Free and never-dispatched slab rows hold init values, which are the identity
elements of their reductions (zero counts, ``+inf`` mins, ``-inf`` maxes,
``-1``-keyed empty ledgers, zero ``_update_count``), so the fold runs over
the whole slab without masking: an evicted row contributes nothing, and an
empty partition's rollup is exactly the merge identity.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import reduce as _reduce
from typing import Any, Dict, Iterable, Optional, Tuple

import jax.numpy as jnp

from metrics_tpu.query.errors import RollupUnsupported

__all__ = ["PartitionRollup", "fold_slab", "fold_states", "merge_folds"]


@dataclass(frozen=True)
class PartitionRollup:
    """One partition's tenants folded into one state, stamped for the cache.

    ``watermark`` is the serving engine's ``(epoch, seq)`` WAL position at
    the instant the slab snapshot was captured (same dispatch-lock window),
    so the rollup is exactly "the fold of everything journaled through seq,
    in lineage epoch". ``follower`` / ``staleness_*`` record WHERE it was
    served — the bounded-staleness evidence the query report surfaces
    per-partition.
    """

    partition: str
    state: Dict[str, Any]
    watermark: Tuple[int, int]
    tenants: int
    follower: bool = False
    node: str = ""
    staleness_seqs: Optional[int] = None
    staleness_s: Optional[float] = None


def _fold_leaf(name: str, reduction: Any, rows: Any, weights: Any, total: Any) -> Any:
    if reduction == "sum":
        return jnp.sum(rows, axis=0)
    if reduction == "max":
        return jnp.max(rows, axis=0)
    if reduction == "min":
        return jnp.min(rows, axis=0)
    if reduction == "mean":
        w = jnp.reshape(weights, weights.shape + (1,) * (rows.ndim - 1))
        return jnp.sum(w * rows, axis=0) / jnp.maximum(total, 1)
    if callable(reduction):
        # singleton pass-through, exactly like a pairwise reduce over one
        # state: reduction callables may canonicalize representation (e.g.
        # topk_merge re-sorts the ledger), and a fold of ONE state must be
        # that state bit-for-bit to stay interchangeable with merge_states
        return rows[0] if rows.shape[0] == 1 else reduction(rows)
    raise RollupUnsupported(
        f"state {name!r} has dist_reduce_fx={reduction!r}: a rollup is a fixed-size "
        "mergeable summary, and 'cat'/None states grow with the stream — use a "
        "sketch-family metric or a reducible scalar state"
    )


def fold_slab(metric: Any, slab: Dict[str, Any]) -> Dict[str, Any]:
    """Fold a stacked ``(K, ...)``-per-leaf state slab into one state pytree."""
    counts = jnp.asarray(slab["_update_count"])
    total = jnp.sum(counts)
    out: Dict[str, Any] = {}
    for name, reduction in metric._reductions.items():
        rows = slab[name]
        if isinstance(rows, list):
            raise RollupUnsupported(
                f"state {name!r} is a list state: not foldable into a rollup"
            )
        out[name] = _fold_leaf(name, reduction, jnp.asarray(rows), counts, total)
    out["_update_count"] = total
    return out


def fold_states(metric: Any, states: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold individually-held state pytrees (eager / tiered tenants) by
    stacking them into a transient slab and reducing it exactly as
    :func:`fold_slab` does — one semantics for both storage regimes."""
    states = list(states)
    if not states:
        return metric.init_state()
    for name in metric._reductions:
        if any(isinstance(s[name], list) for s in states):
            raise RollupUnsupported(
                f"state {name!r} is a list state: not foldable into a rollup"
            )
    slab: Dict[str, Any] = {
        name: jnp.stack([jnp.asarray(s[name]) for s in states])
        for name in metric._reductions
    }
    slab["_update_count"] = jnp.stack(
        [jnp.asarray(s.get("_update_count", 0)) for s in states]
    )
    return fold_slab(metric, slab)


def merge_folds(metric: Any, folds: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge already-folded states left-to-right (ring segments oldest-first
    into the live fold, then tiered tenants) via ``merge_states``."""
    folds = list(folds)
    if not folds:
        return metric.init_state()
    return _reduce(metric.merge_states, folds)
