"""Hierarchical reduce — partition rollups merged through a multi-hop tree.

Rollups merge level by level in a deterministic ``fan_in``-ary tree:
partition order is fixed (ascending partition id of whatever live subset
contributed), each level groups ``fan_in`` consecutive states, and each
group folds in ONE stacked reduction per leaf (:func:`fold_states` — the
same vectorised semantics as the per-partition fold, so a group merge is one
``jnp`` reduction / one ``topk_merge`` call, not ``fan_in - 1`` pairwise
ops). The topology is a pure function of ``(live subset, fan_in)``: every
querier, and the centralized oracle the property suite holds it to, merges
in the same shape.

For the exact reductions the sketch families use (integer sums, elementwise
min/max, register max, in-ledger top-k unions) the result is bit-identical
for ANY tree shape — the tree exists to bound peak stack width and to model
the multi-hop reduction a cross-host deployment runs over the comm plane's
transports, where each hop is one transfer.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

from metrics_tpu.query.rollup import fold_states

__all__ = ["merge_tree"]


def merge_tree(
    metric: Any, states: Sequence[Dict[str, Any]], *, fan_in: int = 4
) -> Tuple[Dict[str, Any], int]:
    """Merge ``states`` through a ``fan_in``-ary tree.

    Returns ``(merged_state, hops)`` where ``hops`` is the number of tree
    levels reduced — 0 for a single state, ``ceil(log_fan_in(n))`` otherwise.
    An empty sequence returns the merge identity (``metric.init_state()``).
    """
    if int(fan_in) < 2:
        raise ValueError(f"`fan_in` must be >= 2, got {fan_in}")
    level: List[Dict[str, Any]] = list(states)
    if not level:
        return metric.init_state(), 0
    hops = 0
    while len(level) > 1:
        nxt: List[Dict[str, Any]] = []
        for i in range(0, len(level), int(fan_in)):
            group = level[i : i + int(fan_in)]
            nxt.append(group[0] if len(group) == 1 else fold_states(metric, group))
        level = nxt
        hops += 1
    return level[0], hops
