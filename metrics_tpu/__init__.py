"""metrics_tpu — TPU-native metrics framework (JAX/XLA/Pallas).

A from-scratch, tpu-first reimplementation of the capabilities of the reference
TorchMetrics library (see SURVEY.md): ~90 stateful module metrics + functional
counterparts over 10 domains, built on one abstraction — a ``Metric`` whose state is a
pytree of ``jax.Array``s, whose ``update``/``compute`` are pure jittable functions, and
whose distributed sync lowers to XLA collectives (psum/pmean/pmax/pmin/all_gather) over
named mesh axes instead of gather-then-reduce.

Top-level export parity with the reference (src/torchmetrics/__init__.py:110-199,
88 names). Optional-dependency metrics (FID/KID/IS/LPIPS, BERTScore, InfoLM, CLIPScore,
PESQ, STOI, MeanAveragePrecision) live in their domain subpackages, mirroring the
reference which also keeps them out of the top-level ``__all__``.
"""

import logging as __logging

__version__ = "0.3.0"

_logger = __logging.getLogger("metrics_tpu")
_logger.addHandler(__logging.StreamHandler())
_logger.setLevel(__logging.INFO)

from metrics_tpu import functional  # noqa: E402
from metrics_tpu import obs  # noqa: E402  (observability layer; not in reference-parity __all__)
from metrics_tpu import comm  # noqa: E402  (collective sync plane; not in reference-parity __all__)
from metrics_tpu import engine  # noqa: E402  (serving runtime; not in reference-parity __all__)
from metrics_tpu import ckpt  # noqa: E402  (durable state plane; not in reference-parity __all__)
from metrics_tpu import sketch  # noqa: E402  (sketch plane; not in reference-parity __all__)
from metrics_tpu import kernels  # noqa: E402  (Pallas TPU kernel plane; not in reference-parity __all__)
from metrics_tpu.aggregation import CatMetric, MaxMetric, MeanMetric, MinMetric, SumMetric  # noqa: E402
from metrics_tpu.audio import (  # noqa: E402
    PermutationInvariantTraining,
    ScaleInvariantSignalDistortionRatio,
    ScaleInvariantSignalNoiseRatio,
    SignalDistortionRatio,
    SignalNoiseRatio,
)
from metrics_tpu.classification import (  # noqa: E402
    AUROC,
    ROC,
    Accuracy,
    AveragePrecision,
    CalibrationError,
    CohenKappa,
    ConfusionMatrix,
    Dice,
    ExactMatch,
    F1Score,
    FBetaScore,
    HammingDistance,
    HingeLoss,
    JaccardIndex,
    MatthewsCorrCoef,
    Precision,
    PrecisionRecallCurve,
    Recall,
    Specificity,
    StatScores,
)
from metrics_tpu.collections import MetricCollection  # noqa: E402
from metrics_tpu.sketch import CardinalitySketch, HeavyHittersSketch, QuantileSketch  # noqa: E402
from metrics_tpu.image import (  # noqa: E402
    ErrorRelativeGlobalDimensionlessSynthesis,
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    SpectralAngleMapper,
    SpectralDistortionIndex,
    StructuralSimilarityIndexMeasure,
    TotalVariation,
    UniversalImageQualityIndex,
)
from metrics_tpu.metric import CompositionalMetric, Metric  # noqa: E402
from metrics_tpu.nominal import (  # noqa: E402
    CramersV,
    PearsonsContingencyCoefficient,
    TheilsU,
    TschuprowsT,
)
from metrics_tpu.regression import (  # noqa: E402
    ConcordanceCorrCoef,
    CosineSimilarity,
    ExplainedVariance,
    KendallRankCorrCoef,
    KLDivergence,
    LogCoshError,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    PearsonCorrCoef,
    R2Score,
    SpearmanCorrCoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
    WeightedMeanAbsolutePercentageError,
)
from metrics_tpu.retrieval import (  # noqa: E402
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
    RetrievalRPrecision,
)
from metrics_tpu.text import (  # noqa: E402
    BLEUScore,
    CharErrorRate,
    CHRFScore,
    ExtendedEditDistance,
    MatchErrorRate,
    Perplexity,
    SacreBLEUScore,
    SQuAD,
    TranslationEditRate,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)
from metrics_tpu.wrappers import (  # noqa: E402
    BootStrapper,
    ClasswiseWrapper,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
)

__all__ = [
    "functional",
    "Accuracy",
    "AUROC",
    "AveragePrecision",
    "BLEUScore",
    "BootStrapper",
    "CalibrationError",
    "CardinalitySketch",
    "CatMetric",
    "ClasswiseWrapper",
    "CharErrorRate",
    "CHRFScore",
    "CompositionalMetric",
    "ConcordanceCorrCoef",
    "CohenKappa",
    "ConfusionMatrix",
    "CosineSimilarity",
    "CramersV",
    "Dice",
    "HeavyHittersSketch",
    "TweedieDevianceScore",
    "ErrorRelativeGlobalDimensionlessSynthesis",
    "ExactMatch",
    "ExplainedVariance",
    "ExtendedEditDistance",
    "F1Score",
    "FBetaScore",
    "HammingDistance",
    "HingeLoss",
    "JaccardIndex",
    "KendallRankCorrCoef",
    "KLDivergence",
    "LogCoshError",
    "MatchErrorRate",
    "MatthewsCorrCoef",
    "MaxMetric",
    "MeanAbsoluteError",
    "MeanAbsolutePercentageError",
    "MeanMetric",
    "MeanSquaredError",
    "MeanSquaredLogError",
    "Metric",
    "MetricCollection",
    "MetricTracker",
    "MinMaxMetric",
    "MinMetric",
    "MultioutputWrapper",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "PearsonCorrCoef",
    "PearsonsContingencyCoefficient",
    "PermutationInvariantTraining",
    "Perplexity",
    "Precision",
    "PrecisionRecallCurve",
    "PeakSignalNoiseRatio",
    "QuantileSketch",
    "R2Score",
    "Recall",
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMRR",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalRecall",
    "RetrievalRPrecision",
    "RetrievalPrecisionRecallCurve",
    "RetrievalRecallAtFixedPrecision",
    "ROC",
    "SacreBLEUScore",
    "SignalDistortionRatio",
    "ScaleInvariantSignalDistortionRatio",
    "ScaleInvariantSignalNoiseRatio",
    "SignalNoiseRatio",
    "SpearmanCorrCoef",
    "Specificity",
    "SpectralAngleMapper",
    "SpectralDistortionIndex",
    "SQuAD",
    "StructuralSimilarityIndexMeasure",
    "StatScores",
    "SumMetric",
    "SymmetricMeanAbsolutePercentageError",
    "TheilsU",
    "TotalVariation",
    "TranslationEditRate",
    "TschuprowsT",
    "UniversalImageQualityIndex",
    "WeightedMeanAbsolutePercentageError",
    "WordErrorRate",
    "WordInfoLost",
    "WordInfoPreserved",
]
