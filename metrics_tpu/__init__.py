"""metrics_tpu — TPU-native metrics framework (JAX/XLA/Pallas).

A from-scratch, tpu-first reimplementation of the capabilities of the reference
TorchMetrics library (see SURVEY.md): ~90 stateful module metrics + functional
counterparts over 10 domains, built on one abstraction — a ``Metric`` whose state is a
pytree of ``jax.Array``s, whose ``update``/``compute`` are pure jittable functions, and
whose distributed sync lowers to XLA collectives (psum/pmean/pmax/pmin/all_gather) over
named mesh axes instead of gather-then-reduce.
"""

import logging as __logging

__version__ = "0.1.0"

_logger = __logging.getLogger("metrics_tpu")
_logger.addHandler(__logging.StreamHandler())
_logger.setLevel(__logging.INFO)

from metrics_tpu.aggregation import CatMetric, MaxMetric, MeanMetric, MinMetric, SumMetric  # noqa: E402
from metrics_tpu.collections import MetricCollection  # noqa: E402
from metrics_tpu.metric import CompositionalMetric, Metric  # noqa: E402
from metrics_tpu.wrappers import (  # noqa: E402
    BootStrapper,
    ClasswiseWrapper,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
)

__all__ = [
    "BootStrapper",
    "CatMetric",
    "ClasswiseWrapper",
    "CompositionalMetric",
    "MaxMetric",
    "MeanMetric",
    "Metric",
    "MetricCollection",
    "MetricTracker",
    "MinMaxMetric",
    "MinMetric",
    "MultioutputWrapper",
    "SumMetric",
]
