"""COCO mean average precision (reference src/torchmetrics/detection/mean_ap.py, 944 LoC).

Design (SURVEY §2.5 "Detection", §7.2 step 9): COCO evaluation is inherently ragged
and host-heavy — detections/groundtruths accumulate as host-side ragged list states
(``dist_reduce_fx=None``; cross-host sync all-gathers the ragged payloads), and the
evaluation protocol runs in vectorized numpy at ``compute()``:

- IoU matrices per (image, class) are one vectorized broadcast (the reference loops
  per pair via torchvision `box_iou`);
- the COCO greedy matcher keeps its sequential score-ordered loop (order-dependent by
  definition) but over a precomputed IoU matrix;
- precision-envelope ("zigzag removal") is one reversed ``np.maximum.accumulate``
  instead of the reference's iterative diff loop (mean_ap.py:881-886);
- the 101-point interpolation follows mean_ap.py:888-894.

Box conversion is implemented natively (xyxy/xywh/cxcywh — the reference defers to
torchvision ``box_convert``, mean_ap.py:444). ``iou_type='segm'`` is also fully
native: RLE encode/decode in vectorized numpy and mask IoU as one dense matmul —
where the reference refuses to run without pycocotools (mean_ap.py:389), segm
mAP here works out of the box with zero optional dependencies.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.metric import Metric


def box_convert(boxes: np.ndarray, in_fmt: str, out_fmt: str = "xyxy") -> np.ndarray:
    """Convert boxes between xyxy / xywh / cxcywh formats (torchvision-compatible)."""
    if in_fmt == out_fmt:
        return boxes
    if in_fmt == "xywh":
        x, y, w, h = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
        return np.stack([x, y, x + w, y + h], axis=-1)
    if in_fmt == "cxcywh":
        cx, cy, w, h = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
        return np.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
    raise ValueError(f"Unsupported box format conversion {in_fmt} -> {out_fmt}")


def box_area(boxes: np.ndarray) -> np.ndarray:
    """Areas of xyxy boxes."""
    return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])


def box_iou(det: np.ndarray, gt: np.ndarray) -> np.ndarray:
    """Pairwise IoU of xyxy boxes, shape [num_det, num_gt]; fully vectorized."""
    lt = np.maximum(det[:, None, :2], gt[None, :, :2])
    rb = np.minimum(det[:, None, 2:], gt[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    union = box_area(det)[:, None] + box_area(gt)[None, :] - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)


def _rle_encode(mask: "np.ndarray") -> np.ndarray:
    """Dense (H, W) binary mask → COCO-style uncompressed RLE run lengths.

    Column-major (Fortran) flatten, alternating zero/one runs starting with a
    zero-run — the same run semantics pycocotools encodes (reference
    mean_ap.py:389 routes through pycocotools; here the whole RLE pipeline is
    native numpy so ``iou_type='segm'`` works without optional deps).
    """
    flat = np.asarray(mask, dtype=bool).ravel(order="F")
    if flat.size == 0:
        return np.zeros(0, np.int64)
    change = np.flatnonzero(flat[1:] != flat[:-1]) + 1
    runs = np.diff(np.concatenate([[0], change, [flat.size]]))
    if flat[0]:
        runs = np.concatenate([[0], runs])
    return runs.astype(np.int64)


def _rle_decode(size: Tuple[int, int], counts: np.ndarray) -> np.ndarray:
    """Uncompressed RLE → flat boolean mask (column-major order)."""
    vals = np.zeros(len(counts), bool)
    vals[1::2] = True
    flat = np.repeat(vals, counts)
    total = int(size[0]) * int(size[1])
    if flat.size != total:  # defensive: runs must tile the mask exactly
        raise ValueError(f"RLE runs sum to {flat.size}, expected {total} for size {size}")
    return flat


# byte → set-bit count, for numpy < 2.0 (np.bitwise_count) fallback
_POPCNT = np.array([bin(i).count("1") for i in range(256)], np.uint16)


def _popcount(a: np.ndarray) -> np.ndarray:
    if hasattr(np, "bitwise_count"):
        return np.bitwise_count(a)
    # numpy<2 fallback: per-byte table lookup (the caller sums over the last
    # axis, so the x8 length change from the uint8 view is transparent)
    return _POPCNT[a.view(np.uint8)]


def _segm_iou(det: Sequence[Tuple], gt: Sequence[Tuple]) -> np.ndarray:
    """Mask IoU, natively (reference mean_ap.py:127-142 calls pycocotools).

    Masks are bit-packed (8 pixels/byte, 32x smaller than the float32 form a
    naive matmul would need) and intersections are exact integer popcounts of
    byte-wise AND, chunked over the detection axis so the pairwise temporary
    stays bounded (~64 MB) even for 100 detections on full-HD masks. Areas and
    unions come from the exact RLE run sums in float64 — no float32 rounding
    at any pixel count.
    """
    def _pack64(masks):
        packed = np.stack([np.packbits(_rle_decode(s, c)) for s, c in masks])
        pad = (-packed.shape[1]) % 8  # widen to uint64 lanes: 8 bytes/popcount op
        if pad:
            packed = np.pad(packed, ((0, 0), (0, pad)))
        return packed.view(np.uint64)

    d_packed, g_packed = _pack64(det), _pack64(gt)
    area_d, area_g = _mask_area(det), _mask_area(gt)
    n_det, n_gt = len(det), len(gt)
    nwords = d_packed.shape[1]
    inter = np.empty((n_det, n_gt), np.float64)
    step = max(1, int(8e6 // max(1, n_gt * nwords)))
    for lo in range(0, n_det, step):
        blk = d_packed[lo : lo + step, None, :] & g_packed[None, :, :]
        inter[lo : lo + step] = _popcount(blk).sum(-1, dtype=np.int64)
    union = area_d[:, None] + area_g[None, :] - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)


def _mask_area(masks: Sequence[Tuple]) -> np.ndarray:
    # one-runs are the odd entries; no decode needed
    return np.asarray([float(c[1::2].sum()) for _, c in masks], dtype=np.float64)


def _validate_structure(preds: Sequence[Dict], targets: Sequence[Dict], iou_type: str = "bbox") -> None:
    """Type/key checks that need no array materialisation — safe to run pre-transfer."""
    item_val_name = "boxes" if iou_type == "bbox" else "masks"

    if not isinstance(preds, Sequence) or isinstance(preds, (str, bytes)):
        raise ValueError("Expected argument `preds` to be of type Sequence")
    if not isinstance(targets, Sequence) or isinstance(targets, (str, bytes)):
        raise ValueError("Expected argument `target` to be of type Sequence")
    if len(preds) != len(targets):
        raise ValueError("Expected argument `preds` and `target` to have the same length")
    if any(not isinstance(p, dict) for p in preds):
        raise ValueError("Expected all elements of `preds` to be of type dict")
    if any(not isinstance(t, dict) for t in targets):
        raise ValueError("Expected all elements of `target` to be of type dict")

    for k in [item_val_name, "scores", "labels"]:
        if any(k not in p for p in preds):
            raise ValueError(f"Expected all dicts in `preds` to contain the `{k}` key")
    for k in [item_val_name, "labels"]:
        if any(k not in p for p in targets):
            raise ValueError(f"Expected all dicts in `target` to contain the `{k}` key")


def _validate_counts(preds: Sequence[Dict], targets: Sequence[Dict], iou_type: str = "bbox") -> None:
    """Per-item boxes/scores/labels count consistency — materialises the arrays."""
    item_val_name = "boxes" if iou_type == "bbox" else "masks"

    # per-item consistency (reference mean_ap.py:173-188)
    for i, item in enumerate(preds):
        n = len(np.asarray(item["labels"]).reshape(-1))
        if len(np.asarray(item["scores"]).reshape(-1)) != n or len(np.asarray(item[item_val_name])) != n:
            raise ValueError(
                f"Input dict at index {i} of `preds` contains inconsistent numbers of"
                f" {item_val_name}/scores/labels"
            )
    for i, item in enumerate(targets):
        if len(np.asarray(item[item_val_name])) != len(np.asarray(item["labels"]).reshape(-1)):
            raise ValueError(
                f"Input dict at index {i} of `target` contains a different number of {item_val_name} and labels"
            )


class MeanAveragePrecision(Metric):
    """COCO mAP / mAR over streaming detections (reference mean_ap.py:199-927).

    Returned dict keys: map, map_50, map_75, map_small, map_medium, map_large,
    mar_{k} per max-detection threshold, mar_small/medium/large, map_per_class,
    mar_{last}_per_class, classes.

    Documented divergence from the reference for non-default
    ``max_detection_thresholds``: the reference summarizes the headline ``map``
    key at a hardcoded ``max_dets=100`` lookup (reference mean_ap.py:697,714
    via the default at :804), so e.g. ``[2, 5, 50]`` yields ``map = -1`` there
    (its other keys — map_50/map_75/area maps and the dynamic ``mar_{k}`` —
    already use ``maxDets[-1]``); here ``map`` follows the COCO/pycocotools
    convention of summarizing at ``maxDets[-1]`` like every other key. The
    conventions coincide whenever 100 is in the list (the default), which is
    pinned against the executed reference in
    tests/parity/test_detection_parity.py.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.detection import MeanAveragePrecision
        >>> preds = [dict(boxes=jnp.array([[10.0, 10.0, 50.0, 50.0]]), scores=jnp.array([0.9]), labels=jnp.array([0]))]
        >>> target = [dict(boxes=jnp.array([[12.0, 10.0, 52.0, 50.0]]), labels=jnp.array([0]))]
        >>> metric = MeanAveragePrecision()
        >>> metric.update(preds, target)
        >>> result = metric.compute()
        >>> round(float(result['map_50']), 4)
        1.0
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    _host_compute = True

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_type: str = "bbox",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        reference_compat: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        allowed_iou_types = ("segm", "bbox")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        self.iou_thresholds = iou_thresholds or np.linspace(0.5, 0.95, round((0.95 - 0.5) / 0.05) + 1).tolist()
        # invariant across every _evaluate_image cell — hoisted out of the matcher
        self._thr_vec = np.asarray(self.iou_thresholds)
        self._iou_range = np.arange(len(self.iou_thresholds))
        self.rec_thresholds = rec_thresholds or np.linspace(0.0, 1.00, round(1.00 / 0.01) + 1).tolist()
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])
        if iou_type not in allowed_iou_types:
            raise ValueError(f"Expected argument `iou_type` to be one of {allowed_iou_types} but got {iou_type}")
        # segm needs NO optional deps here (native RLE + matmul IoU) — the
        # reference gates on pycocotools at this point (ref mean_ap.py:389)
        self.iou_type = iou_type
        self.bbox_area_ranges = {
            "all": (0**2, int(1e5**2)),
            "small": (0**2, 32**2),
            "medium": (32**2, 96**2),
            "large": (96**2, int(1e5**2)),
        }
        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics
        if not isinstance(reference_compat, bool):
            raise ValueError("Expected argument `reference_compat` to be a boolean")
        # Migration switch (default off = COCOeval spec): reproduce torchmetrics
        # v0.12's matcher (reference mean_ap.py:663-689), which deviates from
        # COCOeval three ways — ignored gts removed from candidates entirely (no
        # det soak into area-ignored gts), ties resolved to the FIRST gt
        # (argmax), and STRICT `>` threshold comparison. Deviations are
        # 3e-4..3e-3 on area-range APs / exact-tie scenes; see
        # docs/source/domains/detection.md "Migrating from torchmetrics".
        self.reference_compat = reference_compat

        self.add_state("detections", default=[], dist_reduce_fx=None)
        self.add_state("detection_scores", default=[], dist_reduce_fx=None)
        self.add_state("detection_labels", default=[], dist_reduce_fx=None)
        self.add_state("groundtruths", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_labels", default=[], dist_reduce_fx=None)

    # ------------------------------------------------------------------ update

    @staticmethod
    def _fetch_to_host(items: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        """Overlapped device→host transfer of every array in ``items``.

        The evaluation protocol is host-side (ragged COCO matching), so update must
        land the inputs in host memory. Converting leaf-by-leaf with ``np.asarray``
        issues one *blocking* D2H round-trip per array — dozens per call on an
        accelerator. Starting all copies asynchronously first overlaps the
        transfers, so the call pays one transfer latency instead of N.
        """
        for item in items:
            for value in item.values():
                if hasattr(value, "copy_to_host_async"):
                    value.copy_to_host_async()
        return [{k: (np.asarray(v) if hasattr(v, "shape") else v) for k, v in item.items()} for item in items]

    def update(self, preds: List[Dict[str, Any]], target: List[Dict[str, Any]]) -> None:
        # structural checks first (no array access), then fetch, then the count
        # checks: the full validator materialises every array with np.asarray, which
        # would serialise one blocking D2H round-trip per array and defeat the
        # overlapped transfer
        _validate_structure(preds, target, iou_type=self.iou_type)
        preds = self._fetch_to_host(preds)
        target = self._fetch_to_host(target)
        _validate_counts(preds, target, iou_type=self.iou_type)

        for item in preds:
            self.detections.append(self._get_safe_item_values(item))
            self.detection_labels.append(np.asarray(item["labels"]).reshape(-1))
            self.detection_scores.append(np.asarray(item["scores"]).reshape(-1))

        for item in target:
            self.groundtruths.append(self._get_safe_item_values(item))
            self.groundtruth_labels.append(np.asarray(item["labels"]).reshape(-1))

    def _get_safe_item_values(self, item: Dict[str, Any]):
        if self.iou_type == "bbox":
            boxes = np.asarray(item["boxes"], dtype=np.float64).reshape(-1, 4)
            if boxes.size > 0:
                boxes = box_convert(boxes, in_fmt=self.box_format, out_fmt="xyxy")
            return boxes
        # segm: store RLE-encoded masks (native numpy encoder — no pycocotools)
        masks = []
        for i in np.asarray(item["masks"]):
            masks.append((tuple(i.shape), _rle_encode(i)))
        return tuple(masks)

    # ------------------------------------------------------------------ evaluation protocol

    def _get_classes(self) -> List[int]:
        labels = self.detection_labels + self.groundtruth_labels
        if not labels:
            return []
        return np.unique(np.concatenate([np.asarray(lab).reshape(-1) for lab in labels])).astype(int).tolist()

    def _items_for(self, idx: int, class_id: int, max_det: int):
        """Score-sorted detections and gts of one class in one image."""
        gt_mask = self.groundtruth_labels[idx] == class_id
        det_mask = self.detection_labels[idx] == class_id
        scores = self.detection_scores[idx][det_mask]
        order = np.argsort(-scores, kind="stable")[:max_det]
        scores = scores[order]
        if self.iou_type == "bbox":
            gt = self.groundtruths[idx][gt_mask]
            det = self.detections[idx][det_mask][order]
        else:
            gt = [g for g, m in zip(self.groundtruths[idx], gt_mask) if m]
            det_all = [d for d, m in zip(self.detections[idx], det_mask) if m]
            det = [det_all[i] for i in order]
        return det, gt, scores

    def _areas(self, items) -> np.ndarray:
        if self.iou_type == "bbox":
            return box_area(items) if len(items) else np.zeros(0)
        return _mask_area(items) if len(items) else np.zeros(0)

    def _iou_matrix(self, det, gt) -> np.ndarray:
        if len(det) == 0 or len(gt) == 0:
            return np.zeros((len(det), len(gt)))
        if self.iou_type == "bbox":
            return box_iou(det, gt)
        return _segm_iou(det, gt)

    def _evaluate_image(
        self, items: Tuple, area_range: Tuple[int, int], ious: np.ndarray
    ) -> Optional[Dict[str, np.ndarray]]:
        """COCO matching for one (image, class, area-range) cell (mean_ap.py:562-660)."""
        det, gt, scores = items
        nb_det, nb_gt = len(det), len(gt)
        if nb_det == 0 and nb_gt == 0:
            return None

        nb_iou_thrs = len(self.iou_thresholds)

        gt_areas = self._areas(gt)
        gt_ignore_area = (gt_areas < area_range[0]) | (gt_areas > area_range[1])
        # sort gts ignore-last (stable), permute IoU columns to match
        gtind = np.argsort(gt_ignore_area.astype(np.uint8), kind="stable")
        gt_ignore = gt_ignore_area[gtind]
        ious_sorted = ious[:, gtind] if ious.size else ious

        gt_matches = np.zeros((nb_iou_thrs, nb_gt), dtype=bool)
        det_matches = np.zeros((nb_iou_thrs, nb_det), dtype=bool)
        det_ignore = np.zeros((nb_iou_thrs, nb_det), dtype=bool)

        if ious_sorted.size and self.reference_compat:
            # torchmetrics v0.12 matcher (reference mean_ap.py:663-689,
            # _find_best_gt_match): ignored gts removed from the candidate set
            # entirely, FIRST gt on ties (plain argmax over the zero-masked
            # row), STRICT `>` comparison against the raw threshold. Kept
            # bit-compatible for drop-in migrators; the spec path below is the
            # default.
            thr_vec, iou_range = self._thr_vec, self._iou_range
            for idx_det in range(nb_det):
                avail = ~gt_matches  # (T, G)
                masked = ious_sorted[idx_det][None, :] * (avail & ~gt_ignore[None, :])
                m = np.argmax(masked, axis=1)
                ok = masked[iou_range, m] > thr_vec
                det_matches[:, idx_det] = ok
                gt_matches[ok, m[ok]] = True
                # det_ignore-from-match never fires: ignored gts are not candidates
        elif ious_sorted.size:
            # the greedy matcher is sequential over detections (score order) by
            # definition, but independent across IoU thresholds — vectorise the
            # threshold axis so each det does ONE (T, G) argmax instead of T
            # scalar-loop argmaxes (mean_ap.py:663-689 semantics preserved)
            thr_vec, iou_range = self._thr_vec, self._iou_range
            for idx_det in range(nb_det):
                # COCOeval two-stage preference: best still-unmatched
                # NON-ignored gt first; failing that, the det may soak into the
                # best still-unmatched IGNORED gt (and is then itself ignored
                # rather than becoming an FP). The round-4 soak caught the
                # one-stage form under-scoring area-range APs: an in-range det
                # overlapping only out-of-range gts was counted as an FP where
                # the COCO protocol ignores it. (torchmetrics v0.12 has the
                # same one-stage behavior — here the COCOeval spec wins, see
                # tests/detection/test_coco_protocol_oracle.py.)
                avail = ~gt_matches  # (T, G)
                # COCOeval's scan updates the best match on `>=`, so tied IoUs
                # resolve to the LAST gt in scan order — np.argmax returns the
                # first, hence the reversed-argmax: argmax over the flipped
                # axis, mapped back (verified against the spec oracle on
                # symmetric/duplicate-gt tie scenes)
                last = nb_gt - 1

                def _argmax_last(a):
                    return last - np.argmax(a[:, ::-1], axis=1)

                # match condition is `iou >= min(t, 1-1e-10)` (COCOeval seeds
                # its running best with that value and skips on STRICT less-
                # than), so an IoU exactly at the threshold matches — visible
                # on quantized/axis-aligned boxes where exact ties are common.
                # Unavailable/ignored columns are masked to -1 (NOT 0): with a
                # user-supplied iou threshold of 0.0 a zero-overlap candidate
                # legitimately matches under COCOeval's `>=` scan, but an
                # all-masked row must not — the -1 sentinel keeps the argmax on
                # genuine candidates and fails the >= test when none exist.
                thr_eff = np.minimum(thr_vec, 1 - 1e-10)
                iou_row = ious_sorted[idx_det][None, :]
                masked_valid = np.where(avail & ~gt_ignore[None, :], iou_row, -1.0)
                m1 = _argmax_last(masked_valid)  # (T,)
                ok1 = masked_valid[iou_range, m1] >= thr_eff
                masked_ign = np.where(avail & gt_ignore[None, :], iou_row, -1.0)
                m2 = _argmax_last(masked_ign)
                ok2 = masked_ign[iou_range, m2] >= thr_eff
                m = np.where(ok1, m1, m2)
                matched = ok1 | ok2
                det_ignore[:, idx_det] = matched & ~ok1  # matched an ignored gt
                det_matches[:, idx_det] = matched
                gt_matches[matched, m[matched]] = True

        # unmatched detections outside the area range are ignored
        det_areas = self._areas(det)
        det_ignore_area = (det_areas < area_range[0]) | (det_areas > area_range[1])
        det_ignore = det_ignore | (~det_matches & det_ignore_area[None, :])

        return {
            "dtMatches": det_matches,
            "gtMatches": gt_matches,
            "dtScores": scores,
            "gtIgnore": gt_ignore,
            "dtIgnore": det_ignore,
        }

    def _calculate(self, class_ids: List[int]) -> Tuple[np.ndarray, np.ndarray]:
        """Precision/recall tables [T,R,K,A,M] / [T,K,A,M] (mean_ap.py:736-791)."""
        nb_imgs = len(self.groundtruths)
        max_detections = self.max_detection_thresholds[-1]
        area_ranges = list(self.bbox_area_ranges.values())

        # filter/sort once per (image, class); reused by the IoU cache and all four
        # area ranges below
        items = {
            (idx, class_id): self._items_for(idx, class_id, max_detections)
            for idx in range(nb_imgs)
            for class_id in class_ids
        }
        ious = {key: self._iou_matrix(value[0], value[1]) for key, value in items.items()}

        nb_iou_thrs = len(self.iou_thresholds)
        nb_rec_thrs = len(self.rec_thresholds)
        precision = -np.ones((nb_iou_thrs, nb_rec_thrs, len(class_ids), len(area_ranges), len(self.max_detection_thresholds)))
        recall = -np.ones((nb_iou_thrs, len(class_ids), len(area_ranges), len(self.max_detection_thresholds)))

        rec_thresholds = np.asarray(self.rec_thresholds)

        for idx_cls, class_id in enumerate(class_ids):
            for idx_area, area_range in enumerate(area_ranges):
                evals = [
                    self._evaluate_image(items[(i, class_id)], area_range, ious[(i, class_id)])
                    for i in range(nb_imgs)
                ]
                evals = [e for e in evals if e is not None]
                if not evals:
                    continue
                for idx_max_det, max_det in enumerate(self.max_detection_thresholds):
                    self._accumulate_cell(
                        precision, recall, evals, rec_thresholds, idx_cls, idx_area, idx_max_det, max_det
                    )

        return precision, recall

    def _accumulate_cell(
        self,
        precision: np.ndarray,
        recall: np.ndarray,
        evals: List[Dict[str, np.ndarray]],
        rec_thresholds: np.ndarray,
        idx_cls: int,
        idx_area: int,
        idx_max_det: int,
        max_det: int,
    ) -> None:
        """PR accumulation for one (class, area, max_det) cell (mean_ap.py:827-896)."""
        nb_rec_thrs = len(rec_thresholds)

        det_scores = np.concatenate([e["dtScores"][:max_det] for e in evals])
        inds = np.argsort(-det_scores, kind="mergesort")  # Matlab-consistent ordering
        det_matches = np.concatenate([e["dtMatches"][:, :max_det] for e in evals], axis=1)[:, inds]
        det_ignore = np.concatenate([e["dtIgnore"][:, :max_det] for e in evals], axis=1)[:, inds]
        gt_ignore = np.concatenate([e["gtIgnore"] for e in evals])
        npig = int(np.count_nonzero(~gt_ignore))
        if npig == 0:
            return

        tps = det_matches & ~det_ignore
        fps = ~det_matches & ~det_ignore
        tp_sum = np.cumsum(tps, axis=1, dtype=np.float64)
        fp_sum = np.cumsum(fps, axis=1, dtype=np.float64)

        for idx_iou, (tp, fp) in enumerate(zip(tp_sum, fp_sum)):
            nd = len(tp)
            rc = tp / npig
            pr = tp / (fp + tp + np.finfo(np.float64).eps)
            recall[idx_iou, idx_cls, idx_area, idx_max_det] = rc[-1] if nd else 0

            # precision envelope: pr[i] = max(pr[i:]) — one reversed cummax
            pr = np.maximum.accumulate(pr[::-1])[::-1]

            prec = np.zeros(nb_rec_thrs)
            if self.reference_compat:
                # the reference runs this lookup in float32 (torch.float rc and
                # rec_thresholds): at e.g. rc == 7/10 vs threshold 0.7 the f32
                # values are EQUAL and searchsorted-left includes the entry,
                # while in f64 linspace's 0.7000000000000001 lands one index
                # later (the COCOeval/pycocotools f64 behavior of the default
                # path) — visibly different precision at exact-boundary recalls
                inds_r = np.searchsorted(rc.astype(np.float32), rec_thresholds.astype(np.float32), side="left")
            else:
                inds_r = np.searchsorted(rc, rec_thresholds, side="left")
            valid = inds_r < nd
            prec[valid] = pr[inds_r[valid]]
            precision[idx_iou, :, idx_cls, idx_area, idx_max_det] = prec

    # ------------------------------------------------------------------ summarization

    def _summarize(
        self,
        results: Dict[str, np.ndarray],
        avg_prec: bool = True,
        iou_threshold: Optional[float] = None,
        area_range: str = "all",
        max_dets: int = 100,
    ) -> float:
        """Mean over valid (-1-masked) entries of a results slice (mean_ap.py:691-734)."""
        area_idx = list(self.bbox_area_ranges.keys()).index(area_range)
        mdet_idx = self.max_detection_thresholds.index(max_dets)
        if avg_prec:
            prec = results["precision"]
            if iou_threshold is not None:
                thr = self.iou_thresholds.index(iou_threshold)
                prec = prec[thr, :, :, area_idx, mdet_idx]
            else:
                prec = prec[:, :, :, area_idx, mdet_idx]
        else:
            prec = results["recall"]
            if iou_threshold is not None:
                thr = self.iou_thresholds.index(iou_threshold)
                prec = prec[thr, :, area_idx, mdet_idx]
            else:
                prec = prec[:, :, area_idx, mdet_idx]
        valid = prec[prec > -1]
        return float(valid.mean()) if valid.size else -1.0

    def _summarize_results(self, precisions: np.ndarray, recalls: np.ndarray) -> Dict[str, float]:
        """COCO summary table (mean_ap.py:793-825)."""
        results = {"precision": precisions, "recall": recalls}
        last_max_det = self.max_detection_thresholds[-1]
        out: Dict[str, float] = {}
        out["map"] = self._summarize(results, True, max_dets=last_max_det)
        out["map_50"] = (
            self._summarize(results, True, iou_threshold=0.5, max_dets=last_max_det)
            if 0.5 in self.iou_thresholds
            else -1.0
        )
        out["map_75"] = (
            self._summarize(results, True, iou_threshold=0.75, max_dets=last_max_det)
            if 0.75 in self.iou_thresholds
            else -1.0
        )
        out["map_small"] = self._summarize(results, True, area_range="small", max_dets=last_max_det)
        out["map_medium"] = self._summarize(results, True, area_range="medium", max_dets=last_max_det)
        out["map_large"] = self._summarize(results, True, area_range="large", max_dets=last_max_det)
        for max_det in self.max_detection_thresholds:
            out[f"mar_{max_det}"] = self._summarize(results, False, max_dets=max_det)
        out["mar_small"] = self._summarize(results, False, area_range="small", max_dets=last_max_det)
        out["mar_medium"] = self._summarize(results, False, area_range="medium", max_dets=last_max_det)
        out["mar_large"] = self._summarize(results, False, area_range="large", max_dets=last_max_det)
        return out

    def compute(self) -> Dict[str, Array]:
        classes = self._get_classes()
        precisions, recalls = self._calculate(classes)
        summary = self._summarize_results(precisions, recalls)

        map_per_class = [-1.0]
        mar_per_class = [-1.0]
        if self.class_metrics:
            map_per_class = []
            mar_per_class = []
            last = self.max_detection_thresholds[-1]
            for class_idx in range(len(classes)):
                cls_prec = precisions[:, :, class_idx : class_idx + 1]
                cls_rec = recalls[:, class_idx : class_idx + 1]
                cls_summary = self._summarize_results(cls_prec, cls_rec)
                map_per_class.append(cls_summary["map"])
                mar_per_class.append(cls_summary[f"mar_{last}"])

        # one packed H2D transfer for all float results (then device-side slices)
        # instead of one transfer per key — each tiny transfer costs a full
        # host-device round-trip, which dominates on remote/tunneled accelerators
        keys = list(summary.keys())
        packed = np.concatenate([
            np.asarray([summary[k] for k in keys], dtype=np.float32),
            np.asarray(map_per_class, dtype=np.float32),
            np.asarray(mar_per_class, dtype=np.float32),
        ])
        dev = jnp.asarray(packed)
        metrics: Dict[str, Array] = {k: dev[i] for i, k in enumerate(keys)}
        n, m = len(keys), len(map_per_class)
        metrics["map_per_class"] = dev[n : n + m]
        metrics[f"mar_{self.max_detection_thresholds[-1]}_per_class"] = dev[n + m :]
        metrics["classes"] = jnp.asarray(np.asarray(classes, dtype=np.int32))
        return metrics
