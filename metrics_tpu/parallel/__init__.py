"""Distributed / mesh-parallel machinery (SURVEY §2.3, §2.6).

The reference's parallelism surface is data parallelism: replica-local accumulation +
collective merge at compute. Here that maps onto ``jax.sharding.Mesh`` axes; metric
updates run inside ``shard_map``/``pjit`` and sync with XLA collectives over ICI/DCN.
"""

from metrics_tpu.parallel.sync import in_trace, reduce_in_trace, sync_state_host

__all__ = ["in_trace", "reduce_in_trace", "sync_state_host"]
