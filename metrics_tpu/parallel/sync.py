"""Mesh-aware state synchronisation — the TPU-native distributed backend.

Reference parity: src/torchmetrics/metric.py:365-395 (``_sync_dist``) +
src/torchmetrics/utilities/distributed.py:99-148 (``gather_all_tensors``). The reference
has exactly one collective (all_gather) and reduces the gathered stack in Python.

TPU-native redesign (SURVEY §2.3/§5.8): reducible states never gather — ``sum/mean/max/
min`` lower directly to ``lax.psum/pmax/pmin`` over named mesh axes (strictly less ICI
traffic than gather-then-reduce: O(state) vs O(world·state)). Only ``cat``/``None``
states all_gather. Three execution contexts, one API:

- **in-trace** (inside ``shard_map``/``pjit`` over a Mesh): ``sync_state(state, specs,
  axis_name='dp')`` emits XLA collectives; this is how metric state fuses into a
  training step.
- **host, single-controller**: states computed from globally-sharded arrays are already
  global — sync is the identity.
- **host, multi-controller**: falls back to process-level gather
  (:func:`metrics_tpu.utils.distributed.gather_all_tensors`) + reduction, mirroring the
  reference protocol (incl. ragged pad-to-max).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax import Array

from metrics_tpu.obs import instrument as _obs
from metrics_tpu.obs.registry import OBS as _OBS

AxisName = Union[str, Tuple[str, ...]]

# Reduction registry: maps dist_reduce_fx names to (in-trace collective, host-side stack reduce)
_TRACE_REDUCERS: Dict[str, Callable[[Array, AxisName], Array]] = {
    "sum": lambda x, ax: lax.psum(x, ax),
    "mean": lambda x, ax: lax.pmean(x, ax),
    "max": lambda x, ax: lax.pmax(x, ax),
    "min": lambda x, ax: lax.pmin(x, ax),
}


def reduce_in_trace(x: Array, reduce_fx: Optional[str], axis_name: AxisName) -> Array:
    """Apply one state reduction as an XLA collective over ``axis_name``.

    ``cat``/``None`` → ``all_gather`` (tiled for cat: shards concatenate along dim 0,
    matching the reference's dim-0 cat of the gathered list).
    """
    if _OBS.enabled:
        # trace-time payload accounting: this body runs once per compile, so the
        # recorded bytes price what each EXECUTION of the collective moves per
        # participant (tree_nbytes prices tracers from shape × itemsize); kept in
        # the dedicated per-compile counter, NOT the per-call host counter
        _obs.record_traced_sync_bytes(
            "reduce_in_trace", str(reduce_fx) if not callable(reduce_fx) else "callable", _obs.tree_nbytes(x)
        )
    if reduce_fx in _TRACE_REDUCERS:
        return _TRACE_REDUCERS[reduce_fx](x, axis_name)
    if reduce_fx == "cat":
        return lax.all_gather(x, axis_name, axis=0, tiled=True)
    if reduce_fx is None:
        return lax.all_gather(x, axis_name, axis=0)  # stack: (world, ...)
    if callable(reduce_fx):
        gathered = lax.all_gather(x, axis_name, axis=0)
        return reduce_fx(gathered)
    raise ValueError(f"Unsupported dist_reduce_fx inside trace: {reduce_fx!r}")


def in_trace(x: Any) -> bool:
    return isinstance(x, jax.core.Tracer)


def sync_state_host(
    state: Dict[str, Any],
    reductions: Dict[str, Any],
    gather_fn: Optional[Callable] = None,
    distributed_available_fn: Optional[Callable] = None,
) -> Dict[str, Any]:
    """Host-level all-reduce of a functional state pytree across JAX processes.

    The serving-engine analogue of ``Metric._sync_dist``: the engine holds state as
    explicit pytrees (never inside a ``Metric`` instance), so its ``compute(key)``
    syncs here instead — gather every reducible leaf with
    :func:`metrics_tpu.utils.distributed.gather_all_tensors`, then apply the state's
    registered reduction. ``_update_count`` always sums (each process counted its own
    updates). Single-process (the common case, and every CPU-mesh test) is the
    identity. ``gather_fn`` / ``distributed_available_fn`` are injectable for tests
    and for custom transport.
    """
    from metrics_tpu.utils.data import dim_zero_cat
    from metrics_tpu.utils.distributed import distributed_available, gather_all_tensors

    is_distributed = (distributed_available_fn or distributed_available)()
    if not is_distributed:
        return state
    gather = gather_fn or gather_all_tensors

    if _OBS.enabled:
        _obs.record_sync_bytes("sync_state_host", "state_pytree", _obs.tree_nbytes(state))

    synced = dict(state)
    for name, reduction in reductions.items():
        val = state[name]
        if isinstance(val, list):
            if not val:
                continue
            gathered = gather(dim_zero_cat(val))
            synced[name] = [dim_zero_cat(gathered)]
            continue
        gathered = jnp.stack(gather(jnp.asarray(val)))
        if reduction == "sum":
            synced[name] = jnp.sum(gathered, axis=0)
        elif reduction == "mean":
            synced[name] = jnp.mean(gathered, axis=0)
        elif reduction == "max":
            synced[name] = jnp.max(gathered, axis=0)
        elif reduction == "min":
            synced[name] = jnp.min(gathered, axis=0)
        elif reduction == "cat":
            synced[name] = jnp.concatenate(list(gathered), axis=0)
        elif callable(reduction):
            synced[name] = reduction(gathered)
        else:  # None: stack, matching reduce_in_trace's all_gather
            synced[name] = gathered
    if "_update_count" in state:
        synced["_update_count"] = jnp.sum(jnp.stack(gather(jnp.asarray(state["_update_count"]))), axis=0)
    return synced
