"""Mesh-aware state synchronisation — the TPU-native distributed backend.

Reference parity: src/torchmetrics/metric.py:365-395 (``_sync_dist``) +
src/torchmetrics/utilities/distributed.py:99-148 (``gather_all_tensors``). The reference
has exactly one collective (all_gather) and reduces the gathered stack in Python.

TPU-native redesign (SURVEY §2.3/§5.8): reducible states never gather — ``sum/mean/max/
min`` lower directly to ``lax.psum/pmax/pmin`` over named mesh axes (strictly less ICI
traffic than gather-then-reduce: O(state) vs O(world·state)). Only ``cat``/``None``
states all_gather. Three execution contexts, one API:

- **in-trace** (inside ``shard_map``/``pjit`` over a Mesh): ``sync_state(state, specs,
  axis_name='dp')`` emits XLA collectives; this is how metric state fuses into a
  training step.
- **host, single-controller**: states computed from globally-sharded arrays are already
  global — sync is the identity.
- **host, multi-controller**: falls back to process-level gather
  (:func:`metrics_tpu.utils.distributed.gather_all_tensors`) + reduction, mirroring the
  reference protocol (incl. ragged pad-to-max).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax import Array

AxisName = Union[str, Tuple[str, ...]]

# Reduction registry: maps dist_reduce_fx names to (in-trace collective, host-side stack reduce)
_TRACE_REDUCERS: Dict[str, Callable[[Array, AxisName], Array]] = {
    "sum": lambda x, ax: lax.psum(x, ax),
    "mean": lambda x, ax: lax.pmean(x, ax),
    "max": lambda x, ax: lax.pmax(x, ax),
    "min": lambda x, ax: lax.pmin(x, ax),
}


def reduce_in_trace(x: Array, reduce_fx: Optional[str], axis_name: AxisName) -> Array:
    """Apply one state reduction as an XLA collective over ``axis_name``.

    ``cat``/``None`` → ``all_gather`` (tiled for cat: shards concatenate along dim 0,
    matching the reference's dim-0 cat of the gathered list).
    """
    if reduce_fx in _TRACE_REDUCERS:
        return _TRACE_REDUCERS[reduce_fx](x, axis_name)
    if reduce_fx == "cat":
        return lax.all_gather(x, axis_name, axis=0, tiled=True)
    if reduce_fx is None:
        return lax.all_gather(x, axis_name, axis=0)  # stack: (world, ...)
    if callable(reduce_fx):
        gathered = lax.all_gather(x, axis_name, axis=0)
        return reduce_fx(gathered)
    raise ValueError(f"Unsupported dist_reduce_fx inside trace: {reduce_fx!r}")


def in_trace(x: Any) -> bool:
    return isinstance(x, jax.core.Tracer)
