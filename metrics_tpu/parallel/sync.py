"""Mesh-aware state synchronisation — the TPU-native distributed backend.

Reference parity: src/torchmetrics/metric.py:365-395 (``_sync_dist``) +
src/torchmetrics/utilities/distributed.py:99-148 (``gather_all_tensors``). The reference
has exactly one collective (all_gather) and reduces the gathered stack in Python.

TPU-native redesign (SURVEY §2.3/§5.8): reducible states never gather — ``sum/mean/
max/min`` lower directly to ``lax.psum/pmax/pmin`` over named mesh axes (strictly less
ICI traffic than gather-then-reduce: O(state) vs O(world·state)). Only ``cat``/``None``
states all_gather. Three execution contexts, one API:

- **in-trace** (inside ``shard_map``/``pjit`` over a Mesh): ``reduce_in_trace`` emits
  XLA collectives; this is how metric state fuses into a training step.
- **host, single-controller**: states computed from globally-sharded arrays are already
  global — sync is the identity.
- **host, multi-controller**: :func:`sync_state_host` rides the comm plane
  (:mod:`metrics_tpu.comm`): signature-cached transfer plans, per-state codecs,
  coalesced/chunked collectives, and a timeout → retry → degradation ladder.

Both entries are thin façades over :mod:`metrics_tpu.comm.plane` — the library-wide
sync chokepoint — and keep their pre-comm signatures (``gather_fn`` /
``distributed_available_fn`` stay injectable for tests and custom transports).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
from jax import Array

from metrics_tpu.comm import plane as _plane
from metrics_tpu.obs import instrument as _obs
from metrics_tpu.obs.registry import OBS as _OBS

AxisName = Union[str, Tuple[str, ...]]


def reduce_in_trace(
    x: Array, reduce_fx: Optional[str], axis_name: AxisName, codec: Any = None
) -> Array:
    """Apply one state reduction as an XLA collective over ``axis_name``.

    ``cat``/``None`` → ``all_gather`` (tiled for cat: shards concatenate along dim 0,
    matching the reference's dim-0 cat of the gathered list). Pass ``codec="int8"``
    (or ``"fp16"``) to move gather-style payloads quantized through the collective
    and dequantize on the far side — see :func:`metrics_tpu.comm.plane.reduce_in_trace`.
    """
    # obs trace-time payload accounting happens inside the plane (once per
    # compile, in the dedicated per-compile counter)
    return _plane.reduce_in_trace(x, reduce_fx, axis_name, codec=codec)


def in_trace(x: Any) -> bool:
    return isinstance(x, jax.core.Tracer)


def sync_state_host(
    state: Dict[str, Any],
    reductions: Dict[str, Any],
    gather_fn: Optional[Callable] = None,
    distributed_available_fn: Optional[Callable] = None,
    *,
    transport: Optional[Any] = None,
    config: Optional[Any] = None,
    site: str = "sync_state_host",
) -> Dict[str, Any]:
    """Host-level all-reduce of a functional state pytree across JAX processes.

    The serving-engine analogue of ``Metric._sync_dist``: the engine holds state as
    explicit pytrees (never inside a ``Metric`` instance), so its ``compute(key)``
    syncs here instead. Single-process (the common case, and every CPU-mesh test)
    is the identity.

    Two routes, both through :mod:`metrics_tpu.comm.plane`:

    - ``gather_fn`` injected → the leaf-at-a-time reference protocol
      (:func:`~metrics_tpu.comm.plane.sync_with_gather_fn`); no codecs — an
      injected gather returns decoded peer tensors.
    - otherwise → the planned path (:func:`~metrics_tpu.comm.plane.sync_pytree`):
      cached plan, policy codecs, coalesced collectives, retry/degradation ladder.
      ``transport``/``config`` override the process-wide ``comm.configure`` state.

    ``_update_count`` always sums (each process counted its own updates) — exactly
    once, even when a caller also lists it in ``reductions``.
    """
    from metrics_tpu.utils.distributed import distributed_available

    if gather_fn is not None:
        if not (distributed_available_fn or distributed_available)():
            return state
        if _OBS.enabled:
            _obs.record_sync_bytes(site, "state_pytree", _obs.tree_nbytes(state))
        return _plane.sync_with_gather_fn(state, reductions, gather_fn, site=site)

    cfg = config or _plane.get_config()
    tr = transport or cfg.transport
    if tr is None:
        if not (distributed_available_fn or distributed_available)():
            return state
        tr = _plane.default_transport()
    if _OBS.enabled:
        _obs.record_sync_bytes(site, "state_pytree", _obs.tree_nbytes(state))
    return _plane.sync_pytree(state, reductions, transport=tr, config=cfg, site=site)
