"""Transfer planner: from a state pytree to the minimal sequence of collectives.

Planning happens once per abstract state signature (shape/dtype skeleton —
the same identity jax's jit cache keys on) and is cached; execution happens
every sync. The plan decides, per leaf:

- **route** — fixed-shape array leaves (``sum``/``mean``/``max``/``min``, the
  ``_update_count`` special case, AND callable ``dist_reduce_fx`` — e.g. the
  sketch plane's top-k ledger merge) have identical shapes on every rank by
  construction, so they *coalesce*: all their encoded payloads of one wire
  dtype become a single flat buffer → one collective instead of N. ``cat``/
  ``None``/list leaves are potentially ragged across ranks and go through
  :func:`~metrics_tpu.comm.transport.gather_ragged` individually.
- **codec** — asked of the :class:`~metrics_tpu.comm.codec.CodecPolicy` with
  the leaf's name, reduction, dtype and byte size.
- **chunking** — coalesced buffers larger than ``chunk_bytes`` split into
  bounded slices so one giant leaf can't turn the sync into a single
  monolithic transfer (and so per-chunk retry stays cheap).

The planner sees only shapes; offsets into coalesced buffers come from each
codec's ``payload_specs`` so execution never re-derives layout.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from metrics_tpu.comm.codec import CodecPolicy, get_codec

__all__ = ["LeafPlan", "TransferPlan", "build_plan", "plan_cache_info", "clear_plan_cache"]

_REDUCIBLE = ("sum", "mean", "max", "min")


@dataclass(frozen=True)
class _PayloadSlot:
    """Where one encoded payload of one leaf lives inside a coalesced buffer."""

    leaf: str
    payload_idx: int
    offset: int  # elements into the flat buffer
    size: int  # elements
    shape: Tuple[int, ...]


@dataclass(frozen=True)
class LeafPlan:
    name: str
    route: str  # "coalesce" | "ragged" | "skip"
    codec_name: str
    reduction_tag: str  # str reductions verbatim; "callable"; "none"
    is_list: bool
    shape: Tuple[int, ...]
    dtype: str


@dataclass(frozen=True)
class _CoalescedBuffer:
    """One flat wire buffer: every coalesced payload sharing (wire dtype, op).

    Keying on the reduction op too lets execution reduce the WHOLE gathered
    buffer with a single jnp op and slice leaves out afterwards (``fast``,
    all-lossless buffers) instead of paying a device-put + stack + reduce per
    leaf — bit-identical, since axis-0 reductions are independent per element.
    """

    dtype: str
    op: str  # sum | mean | max | min
    total: int  # elements
    slots: Tuple[_PayloadSlot, ...]
    chunks: Tuple[Tuple[int, int], ...]  # (start, stop) element ranges
    fast: bool  # every slot lossless → buffer-level reduce + slice


@dataclass(frozen=True)
class TransferPlan:
    signature: str
    leaves: Tuple[LeafPlan, ...]
    buffers: Tuple[_CoalescedBuffer, ...]
    has_update_count_extra: bool  # trailing _update_count outside `reductions`

    @property
    def collective_count(self) -> int:
        """Collectives a fault-free execution issues (ragged leaves may add
        shape-gather rounds on top)."""
        return sum(len(b.chunks) for b in self.buffers) + sum(
            len(get_codec(lf.codec_name).payload_specs(lf.shape, np.dtype(lf.dtype)))
            for lf in self.leaves
            if lf.route == "ragged"
        )


def _leaf_meta(val: Any) -> Tuple[Tuple[int, ...], np.dtype, int]:
    if getattr(val, "dtype", None) is None or getattr(val, "shape", None) is None:
        val = np.asarray(val)  # plain Python scalars (e.g. an int _update_count)
    shape = tuple(int(d) for d in val.shape)
    dtype = np.dtype(val.dtype)
    nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize if shape else dtype.itemsize
    return shape, dtype, nbytes


def _reduction_tag(reduction: Any) -> str:
    if reduction is None:
        return "none"
    if isinstance(reduction, str):
        return reduction
    return "callable"


def _signature(state: Dict[str, Any], reductions: Dict[str, Any]) -> str:
    """Abstract identity of (state skeleton, reduction routing) for the cache key."""
    parts: List[str] = []
    for name in sorted(reductions, key=str):
        val = state.get(name)
        if isinstance(val, list):
            if not val:
                parts.append(f"{name}:[]")
                continue
            shapes = ";".join(
                f"{np.dtype(getattr(v, 'dtype', np.float32))}[{'x'.join(map(str, getattr(v, 'shape', ())))}]"
                for v in val
            )
            parts.append(f"{name}:[{shapes}]:{_reduction_tag(reductions[name])}")
        else:
            shape, dtype, _ = _leaf_meta(val)
            parts.append(f"{name}:{dtype}[{'x'.join(map(str, shape))}]:{_reduction_tag(reductions[name])}")
    if "_update_count" in state and "_update_count" not in reductions:
        shape, dtype, _ = _leaf_meta(state["_update_count"])
        parts.append(f"_update_count:{dtype}[{'x'.join(map(str, shape))}]:sum")
    return "|".join(parts)


_PLAN_CACHE: Dict[Tuple[str, CodecPolicy, int, bool, Any], TransferPlan] = {}
_PLAN_LOCK = threading.Lock()
_PLAN_CACHE_MAX = 256
_cache_hits = 0
_cache_misses = 0


def plan_cache_info() -> Dict[str, int]:
    with _PLAN_LOCK:
        return {"size": len(_PLAN_CACHE), "hits": _cache_hits, "misses": _cache_misses}


def clear_plan_cache() -> None:
    global _cache_hits, _cache_misses
    with _PLAN_LOCK:
        _PLAN_CACHE.clear()
        _cache_hits = 0
        _cache_misses = 0


def build_plan(
    state: Dict[str, Any],
    reductions: Dict[str, Any],
    policy: CodecPolicy,
    *,
    chunk_bytes: int = 4 << 20,
    coalesce: bool = True,
    world: Optional[int] = None,
) -> TransferPlan:
    """Plan (cached on the state's abstract signature) the transfers for one sync.

    ``world`` joins the cache key so sub-world plans (the ``live_subset``
    ladder rung executing over an agreed surviving subset) cache exactly like
    full-world plans — the layout itself is world-agnostic (execution sizes
    gathers off ``transport.world_size()``), so each distinct world size costs
    one cache entry, never a rebuild per sync.
    """
    global _cache_hits, _cache_misses
    sig = _signature(state, reductions)
    key = (sig, policy, int(chunk_bytes), bool(coalesce), None if world is None else int(world))
    with _PLAN_LOCK:
        plan = _PLAN_CACHE.get(key)
        if plan is not None:
            _cache_hits += 1
            return plan
        _cache_misses += 1

    leaves: List[LeafPlan] = []
    # dict order of `reductions` is the deterministic leaf order — the same
    # order every retry re-executes, so reductions are reproducible mid-ladder
    items: List[Tuple[str, Any]] = list(reductions.items())
    extra_count = "_update_count" in state and "_update_count" not in reductions
    if extra_count:
        items.append(("_update_count", "sum"))
    for name, reduction in items:
        val = state[name]
        is_list = isinstance(val, list)
        if is_list:
            if not val:
                leaves.append(LeafPlan(name, "skip", "lossless", _reduction_tag(reduction), True, (), "float32"))
                continue
            # planning sees the leaf post-normalisation (dim_zero_cat of the list)
            shapes = [tuple(int(d) for d in getattr(v, "shape", ())) for v in val]
            lead = sum(s[0] if s else 1 for s in shapes)
            rest = shapes[0][1:] if shapes[0] else ()
            shape = (lead, *rest)
            dtype = np.dtype(getattr(val[0], "dtype", np.float32))
            nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        else:
            shape, dtype, nbytes = _leaf_meta(val)
        tag = _reduction_tag(reduction)
        codec_name = policy.choose(name, reduction, dtype, nbytes)
        # callable reductions on ARRAY leaves are fixed-shape by the same
        # argument as the string ops (every rank registered the same default):
        # they ride the coalesced flat-buffer gather and reduce per leaf after
        # slicing (never the buffer-level fast reduce — see below). Only
        # list/cat/None leaves are potentially ragged across ranks.
        fixed_shape = (tag in _REDUCIBLE or tag == "callable") and not is_list
        route = "coalesce" if (fixed_shape and coalesce) else ("ragged" if not fixed_shape else "solo")
        # "solo" (coalescing off) still uses the fixed-shape direct path, as a
        # one-leaf coalesced buffer — keeps execution single-pathed
        leaves.append(LeafPlan(name, "coalesce" if route == "solo" else route, codec_name, tag, is_list, shape, str(dtype)))

    # lay out coalesced buffers: one per (wire dtype, reduction op), in leaf
    # order; with coalescing off, every leaf-payload becomes its own
    # single-slot buffer (offset 0)
    buffers: List[_CoalescedBuffer] = []
    by_key: Dict[Tuple[str, str], List[Tuple[_PayloadSlot, bool]]] = {}
    offsets: Dict[Tuple[str, str], int] = {}
    for lf in leaves:
        if lf.route != "coalesce":
            continue
        codec = get_codec(lf.codec_name)
        for idx, (pshape, pdtype) in enumerate(codec.payload_specs(lf.shape, np.dtype(lf.dtype))):
            d = str(pdtype)
            size = int(np.prod(pshape, dtype=np.int64)) if pshape else 1
            group = (d, lf.reduction_tag)
            if coalesce:
                off = offsets.get(group, 0)
                by_key.setdefault(group, []).append(
                    (_PayloadSlot(lf.name, idx, off, size, tuple(pshape)), codec.lossless)
                )
                offsets[group] = off + size
            else:
                chunk_elems = max(1, int(chunk_bytes) // max(1, np.dtype(d).itemsize))
                slot = _PayloadSlot(lf.name, idx, 0, size, tuple(pshape))
                chunks = tuple((s, min(s + chunk_elems, size)) for s in range(0, size, chunk_elems)) or ((0, 0),)
                buffers.append(
                    _CoalescedBuffer(
                        d,
                        lf.reduction_tag,
                        size,
                        (slot,),
                        chunks,
                        codec.lossless and lf.reduction_tag in _REDUCIBLE,
                    )
                )
    for (d, op), slot_pairs in by_key.items():
        total = offsets[(d, op)]
        chunk_elems = max(1, int(chunk_bytes) // max(1, np.dtype(d).itemsize))
        chunks = tuple((s, min(s + chunk_elems, total)) for s in range(0, total, chunk_elems)) or ((0, 0),)
        buffers.append(
            _CoalescedBuffer(
                d,
                op,
                total,
                tuple(s for s, _ in slot_pairs),
                chunks,
                # buffer-level single-op reduce only exists for the elementwise
                # string ops; a "callable" buffer gathers coalesced but reduces
                # per leaf (the callable sees rank-stacked leaf rows)
                all(l for _, l in slot_pairs) and op in _REDUCIBLE,
            )
        )

    plan = TransferPlan(sig, tuple(leaves), tuple(buffers), extra_count)
    with _PLAN_LOCK:
        if len(_PLAN_CACHE) >= _PLAN_CACHE_MAX:
            _PLAN_CACHE.pop(next(iter(_PLAN_CACHE)))
        _PLAN_CACHE[key] = plan
    return plan
