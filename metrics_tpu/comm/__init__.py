"""metrics_tpu.comm — compressed, fault-tolerant collective sync.

The single chokepoint for all state synchronisation in the library::

    from metrics_tpu import comm

    # opt large float cat-states into blockwise int8 on the wire
    comm.configure(policy=comm.CodecPolicy(lossy="int8"))
    # give multihost gathers a deadline + retry budget
    comm.configure(timeout_s=30.0, max_retries=3)

    engine.compute(k, sync=True)  # the engine's host sync rides the plane
    comm.last_report()            # what it cost / whether it degraded
    # Metric.sync() keeps the reference's leaf-level dist_sync_fn protocol —
    # spans/accounting/transport come from the plane; codecs and the retry
    # ladder apply to the pytree paths (sync_state_host, engine sync)

Three layers (see docs/source/comm.md):

- :mod:`~metrics_tpu.comm.codec` — how a leaf looks on the wire (lossless /
  fp16 / blockwise int8), chosen per state by a dtype- and reduction-aware
  :class:`CodecPolicy`;
- :mod:`~metrics_tpu.comm.plan` — signature-cached transfer plans: coalesce
  small fixed-shape leaves into one buffer per dtype, chunk big ones, route
  ragged ``cat`` states through the pad-to-max (or exact-broadcast) protocol;
- :mod:`~metrics_tpu.comm.transport` — who moves the buffers
  (``multihost_utils``, an in-process :class:`LoopbackWorld`, or injected
  fakes) and the failure vocabulary the retry → degradation ladder in
  :mod:`~metrics_tpu.comm.plane` consumes.

Plus the membership layer (:mod:`~metrics_tpu.comm.membership`): a per-process
:class:`WorldView` fed by attributed collective failures and a two-phase
live-set agreement, which give the ladder its ``live_subset`` rung — survivors
agree on the live sub-world and complete the sync over it (exact for
cumulative mergeable state), and a returning rank rejoins automatically on the
next round.
"""

from metrics_tpu.comm.codec import (
    Codec,
    CodecPolicy,
    EncodedLeaf,
    Fp16Codec,
    Int8BlockCodec,
    LosslessCodec,
    get_codec,
    register_codec,
)
from metrics_tpu.comm.membership import MembershipError, WorldView, agree_live_set, view_for
from metrics_tpu.comm.plan import TransferPlan, build_plan, clear_plan_cache, plan_cache_info
from metrics_tpu.comm.plane import (
    CommConfig,
    SyncReport,
    configure,
    default_transport,
    get_config,
    last_report,
    reduce_in_trace,
    sync_pytree,
    sync_pytree_in_trace,
    sync_state,
    sync_with_gather_fn,
    use_config,
)
from metrics_tpu.comm.transport import (
    DeadPeerTransport,
    FlakyTransport,
    LocalTransport,
    LoopbackWorld,
    MultihostTransport,
    PeerLostError,
    ReplicaFakeTransport,
    ScriptedFakeTransport,
    StallTransport,
    Transport,
    TransportError,
    TransportTimeout,
    gather_ragged,
)

__all__ = [
    "Codec",
    "CodecPolicy",
    "CommConfig",
    "DeadPeerTransport",
    "EncodedLeaf",
    "FlakyTransport",
    "Fp16Codec",
    "Int8BlockCodec",
    "LocalTransport",
    "LoopbackWorld",
    "LosslessCodec",
    "MembershipError",
    "MultihostTransport",
    "PeerLostError",
    "ReplicaFakeTransport",
    "ScriptedFakeTransport",
    "StallTransport",
    "SyncReport",
    "TransferPlan",
    "Transport",
    "TransportError",
    "TransportTimeout",
    "WorldView",
    "agree_live_set",
    "build_plan",
    "clear_plan_cache",
    "configure",
    "default_transport",
    "gather_ragged",
    "get_codec",
    "get_config",
    "last_report",
    "plan_cache_info",
    "reduce_in_trace",
    "register_codec",
    "sync_pytree",
    "sync_pytree_in_trace",
    "sync_state",
    "sync_with_gather_fn",
    "use_config",
    "view_for",
]
