"""The comm plane: every state synchronisation in the library funnels through here.

``Metric._sync_dist``, ``parallel.sync.sync_state_host``, ``reduce_in_trace``
and the engine's ``compute(key, sync=True)`` all land on one of three entry
points:

- :func:`sync_pytree` — the planned, codec'd, fault-tolerant host path:
  plan (cached) → encode → coalesced/ragged collectives → decode → reduce.
- :func:`sync_with_gather_fn` — the leaf-at-a-time compatibility path for
  callers that inject a ``gather_fn``/``dist_sync_fn`` (the reference
  protocol); no codecs (an injected gather returns *decoded* peer tensors),
  same reduction semantics, same obs accounting.
- :func:`reduce_in_trace` — the in-trace (XLA collective) path, with optional
  blockwise-quantized gather for ``cat``-style states (EQuARX-flavored).

Fault tolerance (Prime PCCL-style, arxiv 2505.14065): each host collective runs
under the configured deadline; a failed attempt retries with bounded
exponential backoff, then the sync *degrades* down a ladder —

    full sync (policy codecs) → lossless-only → live-subset → local state + staleness flag

where **live-subset** (membership-capable transports only) runs the two-phase
live-set agreement from :mod:`metrics_tpu.comm.membership`: every survivor
commits to the same agreed sub-world and the plan re-executes over it —
exact for cumulative mergeable state, so one dead host shrinks the aggregate
instead of shattering it into N local answers. Rejoin is automatic: a
returning rank's deposit is picked up by the next agreement round and the
following sync is full-world again. Every rung is visible in obs
(``metrics_tpu_comm_retries_total``, ``_timeouts_total``,
``_degradations_total``, ``_partial_syncs_total``, ``_peer_live``,
``_stale_state``) and in the :class:`SyncReport` returned by
:func:`last_report`. Reduction order is deterministic across retries: the plan
fixes leaf order, ranks always reduce in rank order, and backoff jitter is
deterministic (rank-seeded decorrelation, no wall-clock randomness).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from metrics_tpu.comm import membership as _membership
from metrics_tpu.comm.codec import CodecPolicy, EncodedLeaf, get_codec
from metrics_tpu.comm.membership import MembershipError, WorldView, view_for
from metrics_tpu.comm.plan import TransferPlan, build_plan
from metrics_tpu.comm.transport import (
    LocalTransport,
    MultihostTransport,
    PeerLostError,
    Transport,
    TransportError,
    TransportTimeout,
    gather_ragged,
    set_call_cancel_event,
)
from metrics_tpu.obs import instrument as _obs
from metrics_tpu.obs.registry import OBS as _OBS

__all__ = [
    "CommConfig",
    "SyncReport",
    "configure",
    "default_transport",
    "get_config",
    "last_report",
    "reduce_in_trace",
    "sync_pytree",
    "sync_with_gather_fn",
    "use_config",
]


# ----------------------------------------------------------------- configuration


@dataclass
class CommConfig:
    """Process-wide comm-plane knobs (see :func:`configure`).

    The default is deliberately conservative: lossless everywhere, coalesced,
    no deadline (a host gather blocks like it always did), degradation on.
    """

    policy: CodecPolicy = field(default_factory=CodecPolicy)
    chunk_bytes: int = 4 << 20
    coalesce: bool = True
    timeout_s: Optional[float] = None
    max_retries: int = 2
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    degrade: bool = True
    transport: Optional[Transport] = None
    # membership / live-subset rung: on membership-capable transports, a sync
    # that loses peers agrees on the surviving live set and completes over it
    # instead of falling to local state — as long as at least
    # max(2, min_quorum) ranks survive. membership_deadline_s bounds each
    # agreement phase (defaults to timeout_s, else 1s). The happy path pays
    # only attr-loads: no agreement round runs while the view is all-live.
    membership: bool = True
    min_quorum: int = 2
    membership_deadline_s: Optional[float] = None
    # observer hook: called with every published SyncReport (success, degraded
    # or stale) — how health machinery (e.g. the engine's comm circuit breaker,
    # metrics_tpu.guard) watches sync outcomes without polling last_report().
    # Exceptions are absorbed + rank_zero_warn'ed (the guard plane's
    # on_health_transition contract): observation must never fail a sync.
    on_report: Optional[Callable[["SyncReport"], None]] = None


_CONFIG = CommConfig()
_CONFIG_LOCK = threading.Lock()


def get_config() -> CommConfig:
    with _CONFIG_LOCK:
        return _CONFIG


def configure(**kwargs: Any) -> CommConfig:
    """Replace fields of the process-wide :class:`CommConfig`; returns the
    previous config so callers can restore it."""
    global _CONFIG
    with _CONFIG_LOCK:
        prev = _CONFIG
        _CONFIG = replace(_CONFIG, **kwargs)
    return prev


class use_config:
    """Context manager: run a block under a temporary comm config."""

    def __init__(self, **kwargs: Any) -> None:
        self._kwargs = kwargs
        self._prev: Optional[CommConfig] = None

    def __enter__(self) -> CommConfig:
        self._prev = configure(**self._kwargs)
        return get_config()

    def __exit__(self, *exc: Any) -> None:
        global _CONFIG
        with _CONFIG_LOCK:
            _CONFIG = self._prev


def default_transport() -> Transport:
    """Multihost when the JAX runtime says so, else the world-of-one identity."""
    try:
        import jax

        world = jax.process_count()
    except Exception:  # noqa: BLE001 — uninitialised runtime: act single-process
        world = 1
    return MultihostTransport() if world > 1 else LocalTransport()


# ----------------------------------------------------------------- sync reports


@dataclass
class SyncReport:
    """What one :func:`sync_pytree` call did — the non-obs view of the ladder."""

    site: str = "comm.sync"
    world: int = 1
    raw_bytes: int = 0
    wire_bytes: int = 0
    retries: int = 0
    timeouts: int = 0
    degraded_step: str = "none"  # none | lossless_only | live_subset | local_state
    stale: bool = False
    # membership outcome: which ranks the agreed live set excluded, and how
    # many ranks actually contributed state (== world on a full-world sync)
    peers_lost: Tuple[int, ...] = ()
    world_live: int = 0

    @property
    def world_size(self) -> int:
        return self.world

    @property
    def compression_ratio(self) -> float:
        return self.raw_bytes / self.wire_bytes if self.wire_bytes else 1.0


_LAST_REPORT: Optional[SyncReport] = None
_REPORT_LOCK = threading.Lock()


def last_report() -> Optional[SyncReport]:
    """The most recent :class:`SyncReport` (best-effort under concurrency)."""
    with _REPORT_LOCK:
        return _LAST_REPORT


def _publish(report: SyncReport, config: Optional[CommConfig] = None) -> None:
    global _LAST_REPORT
    with _REPORT_LOCK:
        _LAST_REPORT = report
    hook = config.on_report if config is not None else None
    if hook is not None:
        try:
            hook(report)
        except Exception as exc:  # noqa: BLE001 — observation must never fail a sync
            from metrics_tpu.utils import rank_zero_warn

            rank_zero_warn(
                f"comm on_report observer raised {type(exc).__name__}: {exc} — "
                "report absorbed; a buggy observer must not take the sync path down"
            )


# ----------------------------------------------------------------- transport wrappers


class _TimeoutTransport(Transport):
    """Run each collective under a deadline in a worker thread.

    The underlying call cannot be cancelled outright (a real multihost
    collective has no abort); on timeout the worker is *abandoned safely*:

    - every call is stamped with a generation; a timeout bumps it, so a late
      completion can never publish its result into a later attempt's hands;
    - the worker's cooperative cancel event is set — in-process transports
      check it before touching shared barriers, so a late-running abandoned
      call cannot deposit into a fresh round;
    - the inner transport is ``reset()`` (when it supports it) so an abandoned
      waiter cannot keep occupying a barrier seat.

    One instance is shared across a sync's retries — that is what makes the
    generation stamp meaningful.
    """

    def __init__(self, inner: Transport, timeout_s: Optional[float]) -> None:
        self._inner = inner
        self._timeout_s = timeout_s
        self._gen = 0
        self._gen_lock = threading.Lock()

    @property
    def name(self) -> str:  # type: ignore[override]
        return self._inner.name

    @property
    def supports_broadcast(self) -> bool:  # type: ignore[override]
        return self._inner.supports_broadcast

    @property
    def rank(self) -> Any:
        return getattr(self._inner, "rank", None)

    def world_size(self) -> int:
        return self._inner.world_size()

    def _call(self, fn: Callable, *args: Any) -> Any:
        if not self._timeout_s:
            return fn(*args)
        with self._gen_lock:
            self._gen += 1
            gen = self._gen
        box: List[Any] = [None, None, False]
        done = threading.Event()
        cancel = threading.Event()

        def _run() -> None:
            set_call_cancel_event(cancel)
            try:
                out, exc = fn(*args), None
            except BaseException as e:  # noqa: BLE001 — re-raised below
                out, exc = None, e
            finally:
                set_call_cancel_event(None)
            with self._gen_lock:
                if self._gen == gen:
                    box[0], box[1], box[2] = out, exc, True
            done.set()

        t = threading.Thread(target=_run, daemon=True)
        t.start()
        done.wait(self._timeout_s)
        with self._gen_lock:
            landed = box[2]
            if not landed:
                self._gen += 1  # stamp the call abandoned before the worker can land
        if landed:
            if box[1] is not None:
                raise box[1]
            return box[0]
        cancel.set()
        reset = getattr(self._inner, "reset", None)
        if reset is not None:
            reset()
        raise TransportTimeout(f"collective exceeded {self._timeout_s}s deadline")

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        return self._call(self._inner.allgather, x)

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        return self._call(self._inner.broadcast_from, x, root, shape, dtype)


class _MeteredTransport(Transport):
    """Counts the bytes this rank puts on the wire (sends only)."""

    def __init__(self, inner: Transport) -> None:
        self._inner = inner
        self.sent_bytes = 0

    @property
    def name(self) -> str:  # type: ignore[override]
        return self._inner.name

    @property
    def supports_broadcast(self) -> bool:  # type: ignore[override]
        return self._inner.supports_broadcast

    @property
    def rank(self) -> Any:
        return getattr(self._inner, "rank", None)

    def world_size(self) -> int:
        return self._inner.world_size()

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        self.sent_bytes += int(np.asarray(x).nbytes)
        return self._inner.allgather(x)

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        if x is not None:
            self.sent_bytes += int(np.asarray(x).nbytes)
        return self._inner.broadcast_from(x, root, shape, dtype)


# ----------------------------------------------------------------- reductions

_REDUCIBLE_OPS = {"sum", "mean", "max", "min"}


def _reduce_rows(tag: str, reduction: Any, rows: List[Any], is_list: bool) -> Any:
    """Reduce rank-ordered rows with the pre-comm ``sync_state_host`` semantics."""
    import jax.numpy as jnp

    from metrics_tpu.utils.data import dim_zero_cat

    rows_j = [jnp.asarray(r) for r in rows]
    if is_list:
        return [dim_zero_cat(rows_j)]
    if tag in _REDUCIBLE_OPS:
        stacked = jnp.stack(rows_j)
        return {"sum": jnp.sum, "mean": jnp.mean, "max": jnp.max, "min": jnp.min}[tag](stacked, axis=0)
    if tag == "cat":
        return jnp.concatenate(rows_j, axis=0)
    if tag == "callable":
        return reduction(jnp.stack(rows_j))
    # None: stack to (world, ...), matching reduce_in_trace's all_gather
    return jnp.stack(rows_j)


# ----------------------------------------------------------------- planned execution


def _execute_plan(
    plan: TransferPlan,
    state: Dict[str, Any],
    reductions: Dict[str, Any],
    transport: Transport,
) -> Tuple[Dict[str, Any], int]:
    """One fault-free pass: encode → collectives → decode → reduce.

    Returns ``(synced_state, raw_bytes)``; wire bytes are metered on the
    transport by the caller. Raises ``TransportError``/``TransportTimeout``
    through from the transport — retry policy lives in :func:`sync_pytree`.
    """
    from metrics_tpu.utils.data import dim_zero_cat

    world = transport.world_size()
    encoded: Dict[str, EncodedLeaf] = {}
    raw_bytes = 0
    for lf in plan.leaves:
        if lf.route == "skip":
            continue
        val = state[lf.name]
        if lf.is_list:
            val = dim_zero_cat(val)
        enc = get_codec(lf.codec_name).encode(np.asarray(val))
        encoded[lf.name] = enc
        raw_bytes += enc.raw_nbytes

    import jax.numpy as jnp

    # payload rows per (leaf, payload_idx), rank-ordered (lossy coalesced leaves)
    payload_rows: Dict[Tuple[str, int], List[np.ndarray]] = {}
    # leaves finished by the buffer-level fast path
    fast_done: Dict[str, Any] = {}
    _ops = {"sum": jnp.sum, "mean": jnp.mean, "max": jnp.max, "min": jnp.min}

    # coalesced buffers: one flat array per (wire dtype, reduction op), chunked
    for buf in plan.buffers:
        flat = np.empty(buf.total, dtype=np.dtype(buf.dtype))
        for slot in buf.slots:
            flat[slot.offset : slot.offset + slot.size] = encoded[slot.leaf].payloads[slot.payload_idx].ravel()
        rank_parts: List[List[np.ndarray]] = [[] for _ in range(world)]
        for start, stop in buf.chunks:
            rows = transport.allgather(flat[start:stop])
            for r in range(world):
                row = np.asarray(rows[r]).ravel()
                if row.size != stop - start:
                    # the coalesced route is only sound when every rank holds
                    # identically-shaped leaves (true by construction for
                    # registered fixed-shape states). A custom callable-reduced
                    # state whose shape DIVERGES across ranks would otherwise
                    # be sliced with local offsets and reduced silently wrong —
                    # make it a loud transport failure instead.
                    raise TransportError(
                        f"coalesced sync: rank {r} gathered {row.size} elements for a "
                        f"{stop - start}-element chunk of buffer ({buf.dtype}, {buf.op}) — "
                        "a fixed-shape state's shape diverged across ranks (leaves "
                        f"{[s.leaf for s in buf.slots]})"
                    )
                rank_parts[r].append(row)
        rank_flats = [
            parts[0] if len(parts) == 1 else np.concatenate(parts) for parts in rank_parts
        ]
        if buf.fast:
            # all-lossless buffer: ONE device put + ONE reduction for every
            # slotted leaf, then slice — bit-identical to per-leaf reduction
            # (axis-0 reduces are independent per element), ~W× fewer jnp ops
            reduced = _ops[buf.op](jnp.asarray(np.stack(rank_flats)), axis=0)
            for slot in buf.slots:
                fast_done[slot.leaf] = reduced[slot.offset : slot.offset + slot.size].reshape(slot.shape)
            continue
        for r, rank_flat in enumerate(rank_flats):
            for slot in buf.slots:
                payload_rows.setdefault((slot.leaf, slot.payload_idx), [None] * world)[r] = rank_flat[
                    slot.offset : slot.offset + slot.size
                ].reshape(slot.shape)

    # ragged leaves: per-leaf shape gather + per-payload ragged gather
    decoded_rows: Dict[str, List[np.ndarray]] = {}
    rank = getattr(transport, "rank", None)
    for lf in plan.leaves:
        if lf.route != "ragged":
            continue
        enc = encoded[lf.name]
        codec = get_codec(lf.codec_name)
        shape_rows = transport.allgather(np.asarray(enc.shape, dtype=np.int64))
        peer_shapes = [tuple(int(d) for d in s) for s in shape_rows]
        gathered_payloads = [
            gather_ragged(transport, np.asarray(p), rank=rank) for p in enc.payloads
        ]
        decoded_rows[lf.name] = [
            codec.decode(
                EncodedLeaf(
                    lf.codec_name,
                    tuple(gathered_payloads[i][r] for i in range(len(enc.payloads))),
                    peer_shapes[r],
                    np.dtype(lf.dtype),
                )
            )
            for r in range(world)
        ]

    # decode + reduce, in plan (== reduction-dict) order; rank order is fixed
    synced = dict(state)
    for lf in plan.leaves:
        if lf.route == "skip":
            continue
        if lf.name in fast_done:
            synced[lf.name] = fast_done[lf.name]
            continue
        codec = get_codec(lf.codec_name)
        if lf.route == "coalesce":
            nP = len(codec.payload_specs(lf.shape, np.dtype(lf.dtype)))
            rows = [
                codec.decode(
                    EncodedLeaf(
                        lf.codec_name,
                        tuple(payload_rows[(lf.name, i)][r] for i in range(nP)),
                        lf.shape,
                        np.dtype(lf.dtype),
                    )
                )
                for r in range(world)
            ]
        else:
            rows = decoded_rows[lf.name]
        reduction = reductions.get(lf.name, "sum")  # the trailing _update_count sums
        synced[lf.name] = _reduce_rows(lf.reduction_tag, reduction, rows, lf.is_list)
    return synced, raw_bytes


def _plan_has_lossy(plan: TransferPlan) -> bool:
    return any(not get_codec(lf.codec_name).lossless for lf in plan.leaves if lf.route != "skip")


def _backoff_s(cfg: CommConfig, attempt: int, rank: int) -> float:
    """Deterministic rank-seeded decorrelated backoff jitter.

    N ranks that lost the same peer fail the same collective at the same
    instant; a jitter-free ladder would retry them in lockstep. Seeding the
    jitter from ``(rank, attempt)`` de-synchronises the retry storm while
    staying bit-reproducible in tests — no wall-clock randomness.
    """
    base = cfg.backoff_base_s * (2**attempt)
    rng = np.random.default_rng(int(rank + 1) * 1_000_003 + int(attempt))
    return float(min(cfg.backoff_max_s, base * (0.5 + rng.random())))


def _record_peer_liveness(view: WorldView) -> None:
    lost = set(view.lost())
    for peer in range(view.world):
        _obs.record_comm_peer_live(peer, peer not in lost)


def sync_pytree(
    state: Dict[str, Any],
    reductions: Dict[str, Any],
    *,
    transport: Optional[Transport] = None,
    config: Optional[CommConfig] = None,
    site: str = "comm.sync",
) -> Dict[str, Any]:
    """Host-level all-reduce of a functional state pytree through the comm plane.

    The planned path: cached transfer plan, per-leaf codecs, coalesced/chunked
    collectives, and the retry → degradation ladder documented on this module.
    Returns the synced state; inspect :func:`last_report` (or the obs comm
    counters) for what it took to get it.
    """
    cfg = config or get_config()
    tr = transport or cfg.transport or default_transport()
    world_full = tr.world_size()
    report = SyncReport(site=site, world=world_full)

    # membership engages only on capable transports with a real world — the
    # happy path's whole cost is these attr-loads plus one has_lost() check
    mview: Optional[WorldView] = None
    if cfg.membership and world_full > 1 and getattr(tr, "supports_membership", False):
        mview = view_for(tr)

    plan = build_plan(
        state, reductions, cfg.policy, chunk_bytes=cfg.chunk_bytes, coalesce=cfg.coalesce, world=world_full
    )
    steps: List[Tuple[str, CodecPolicy]] = [("full", cfg.policy)]
    if _plan_has_lossy(plan):
        steps.append(("lossless_only", cfg.policy.all_lossless()))

    rank = getattr(tr, "rank", None) or 0
    quorum = max(2, int(cfg.min_quorum))
    agree_deadline = cfg.membership_deadline_s or cfg.timeout_s or 1.0
    subset_recorded = False

    with _obs.comm_span("comm.sync", site=site, world=report.world):
        # bounded (agreement + execution) passes: a degraded episode's live set
        # can only shrink, so the ladder always terminates
        for _pass in range(world_full + cfg.max_retries + 2):
            agreed: Optional[Tuple[int, ...]] = None
            if mview is not None and mview.has_lost():
                # known-lost peers: agree BEFORE payload, so the sync never
                # stalls a full-world deadline on a peer it knows is gone —
                # and a rejoiner's board deposit gets picked up right here
                try:
                    agreed = _membership.agree_live_set(tr, mview, deadline_s=agree_deadline)
                except MembershipError:
                    break
                _record_peer_liveness(mview)
                if len(agreed) < quorum:
                    break
            subset_mode = agreed is not None and len(agreed) < world_full
            exec_tr: Transport = tr.subset(agreed) if subset_mode else tr  # type: ignore[attr-defined]
            if subset_mode and not subset_recorded:
                subset_recorded = True
                _obs.record_comm_degradation(site, "live_subset")
                _obs.record_comm_partial_sync(site)
            # the live_subset rung sits between lossless_only and local_state:
            # subset execution is lossless-only by construction
            pass_steps = [("live_subset", cfg.policy.all_lossless())] if subset_mode else steps
            # ONE deadline wrapper per pass: its generation stamp spans retries,
            # so an abandoned attempt's late completion is always discarded
            deadline_tr = _TimeoutTransport(exec_tr, cfg.timeout_s)
            failure: Optional[BaseException] = None
            for step_idx, (step_name, policy) in enumerate(pass_steps):
                step_plan = (
                    plan
                    if step_name == "full"
                    else build_plan(
                        state,
                        reductions,
                        policy,
                        chunk_bytes=cfg.chunk_bytes,
                        coalesce=cfg.coalesce,
                        world=exec_tr.world_size(),
                    )
                )
                for attempt in range(cfg.max_retries + 1):
                    metered = _MeteredTransport(deadline_tr)
                    try:
                        synced, raw = _execute_plan(step_plan, state, reductions, metered)
                    except PeerLostError as exc:
                        failure = exc
                        if mview is not None and exc.peers:
                            mview.mark_lost(exc.peers)
                            _record_peer_liveness(mview)
                        break  # membership broke: same-step retries cannot succeed
                    except TransportTimeout as exc:
                        failure = exc
                        report.timeouts += 1
                        _obs.record_comm_timeout(site)
                    except TransportError as exc:
                        failure = exc
                    else:
                        if subset_mode:
                            report.degraded_step = "live_subset"
                            report.peers_lost = tuple(r for r in range(world_full) if r not in agreed)
                            report.world_live = len(agreed)
                        else:
                            report.world_live = world_full
                            if agreed is not None:
                                report.degraded_step = "none"  # world fully restored
                        report.raw_bytes = raw
                        report.wire_bytes = metered.sent_bytes
                        _obs.record_comm_payload(site, raw, metered.sent_bytes)
                        _obs.set_comm_stale(site, False)
                        _publish(report, cfg)
                        return synced
                    if attempt < cfg.max_retries:
                        report.retries += 1
                        _obs.record_comm_retry(site)
                        time.sleep(_backoff_s(cfg, attempt, rank))
                if isinstance(failure, PeerLostError) and mview is not None:
                    break  # live_subset is the next rung: go re-agree
                if step_idx + 1 < len(pass_steps):
                    report.degraded_step = pass_steps[step_idx + 1][0]
                    _obs.record_comm_degradation(site, pass_steps[step_idx + 1][0])
            if mview is None or not mview.has_lost():
                break  # no membership signal to act on: the ladder is exhausted

    # ladder exhausted: serve local state, flagged stale
    if not cfg.degrade:
        _publish(report, cfg)
        raise TransportError(f"comm sync at {site!r} failed after the full retry ladder (degrade=False)")
    report.degraded_step = "local_state"
    report.stale = True
    if mview is not None:
        report.peers_lost = mview.lost()
        # A rank that fell all the way to local state learned nothing reliable
        # about the world: only *attributed* failures (PeerLostError.peers)
        # marked peers lost, and a rank whose collectives all died as
        # unattributed timeouts exits with an EMPTY lost set — its next sync
        # would then skip agreement and stall a full-world collective while
        # the peers that DID attribute the failure agree on a subset without
        # it. Poison the view (the restarting-process contract of
        # suspect_all) so the next sync re-agrees from the board regardless
        # of which side of the attribution race this rank landed on.
        mview.suspect_all()
    _obs.record_comm_degradation(site, "local_state")
    _obs.set_comm_stale(site, True)
    _publish(report, cfg)
    return dict(state)


# ----------------------------------------------------------------- gather-fn compatibility path


def sync_with_gather_fn(
    state: Dict[str, Any],
    reductions: Dict[str, Any],
    gather_fn: Callable,
    *,
    site: str = "sync_state_host",
) -> Dict[str, Any]:
    """Leaf-at-a-time sync for callers injecting a reference-protocol gather.

    An injected ``gather_fn`` returns already-decoded peer tensors, so no codec
    applies; semantics match the pre-comm ``sync_state_host`` exactly — except
    the ``_update_count`` special case now only fires when the key is *not*
    already in ``reductions`` (it used to be reduced twice).
    """
    import jax.numpy as jnp

    from metrics_tpu.utils.data import dim_zero_cat

    if _OBS.enabled:
        nbytes = _obs.tree_nbytes(state)
        _obs.record_comm_payload(site, nbytes, nbytes)
    with _obs.comm_span("comm.sync_gather_fn", site=site):
        synced = dict(state)
        for name, reduction in reductions.items():
            val = state[name]
            if isinstance(val, list):
                if not val:
                    continue
                synced[name] = [dim_zero_cat(gather_fn(dim_zero_cat(val)))]
                continue
            tag = "callable" if callable(reduction) else ("none" if reduction is None else reduction)
            synced[name] = _reduce_rows(tag, reduction, gather_fn(jnp.asarray(val)), False)
        if "_update_count" in state and "_update_count" not in reductions:
            synced["_update_count"] = jnp.sum(
                jnp.stack(gather_fn(jnp.asarray(state["_update_count"]))), axis=0
            )
    return synced


def gather_metric_leaves(
    input_dict: Dict[str, Any],
    gather_fn: Callable,
    group: Optional[Any] = None,
    *,
    site: str = "Metric._sync_dist",
) -> Dict[str, Any]:
    """``Metric._sync_dist``'s gather step, routed through the comm plane.

    Applies ``gather_fn`` to every array leaf (the reference ``dist_sync_fn``
    protocol) under a comm span, with raw==wire byte accounting — an injected
    gather moves decoded tensors, so there is nothing to compress here; the
    default ``gather_all_tensors`` rides the configured transport underneath.
    """
    import jax

    from metrics_tpu.utils.data import apply_to_collection

    if _OBS.enabled:
        nbytes = _obs.tree_nbytes(input_dict)
        _obs.record_comm_payload(site, nbytes, nbytes)
    with _obs.comm_span("comm.gather_leaves", site=site):
        return apply_to_collection(input_dict, jax.Array, gather_fn, group=group)


# ----------------------------------------------------------------- in-trace path


def sync_pytree_in_trace(
    state: Dict[str, Any],
    reductions: Dict[str, Any],
    axis_name: Any,
    codec: Any = None,
) -> Dict[str, Any]:
    """In-trace pytree sync: one XLA collective per state over ``axis_name``.

    The traced twin of :func:`sync_pytree` (``Metric.sync_state`` delegates
    here): list states ``dim_zero_cat`` then gather-as-cat; everything else
    routes through :func:`reduce_in_trace`. ``codec`` applies to gather-style
    leaves only (see :func:`reduce_in_trace`).
    """
    from metrics_tpu.utils.data import dim_zero_cat

    synced = dict(state)
    for name, reduction in reductions.items():
        val = state[name]
        if isinstance(val, list):
            synced[name] = val if not val else [reduce_in_trace(dim_zero_cat(val), "cat", axis_name, codec=codec)]
        else:
            synced[name] = reduce_in_trace(val, reduction, axis_name, codec=codec)
    return synced


def sync_state(
    state: Dict[str, Any],
    reductions: Dict[str, Any],
    *,
    axis_name: Any = None,
    transport: Optional[Transport] = None,
    config: Optional[CommConfig] = None,
    site: str = "comm.sync",
    codec: Any = None,
) -> Dict[str, Any]:
    """One entry, both execution contexts: in-trace when ``axis_name`` is given
    (XLA collectives over the mesh axis), host-planned otherwise."""
    if axis_name is not None:
        return sync_pytree_in_trace(state, reductions, axis_name, codec=codec)
    return sync_pytree(state, reductions, transport=transport, config=config, site=site)


def reduce_in_trace(x: Any, reduce_fx: Any, axis_name: Any, codec: Any = None) -> Any:
    """Apply one state reduction as an XLA collective over ``axis_name``.

    ``sum``/``mean``/``max``/``min`` lower to ``lax.psum``/``pmean``/``pmax``/
    ``pmin`` and are always lossless (a quantized all-reduce needs ring
    rewrites XLA owns; see docs/source/comm.md). ``cat``/``None``/callable
    gather — and may gather *quantized*: pass ``codec="int8"`` (or an
    :class:`~metrics_tpu.comm.codec.Int8BlockCodec`) to ship blockwise int8
    codes + scales through the all-gather and dequantize on the far side,
    EQuARX-style.
    """
    import jax.numpy as jnp
    from jax import lax

    if _OBS.enabled:
        # trace-time payload accounting: this body runs once per compile, so the
        # recorded bytes price what each EXECUTION of the collective moves per
        # participant; kept in the dedicated per-compile counter, NOT the
        # per-call host counter
        _obs.record_traced_sync_bytes(
            "reduce_in_trace", str(reduce_fx) if not callable(reduce_fx) else "callable", _obs.tree_nbytes(x)
        )
    if reduce_fx == "sum":
        return lax.psum(x, axis_name)
    if reduce_fx == "mean":
        return lax.pmean(x, axis_name)
    if reduce_fx == "max":
        return lax.pmax(x, axis_name)
    if reduce_fx == "min":
        return lax.pmin(x, axis_name)
    if reduce_fx not in ("cat", None) and not callable(reduce_fx):
        raise ValueError(f"Unsupported dist_reduce_fx inside trace: {reduce_fx!r}")

    n = int(np.prod(x.shape, dtype=np.int64)) if x.ndim else 1
    c = get_codec(codec) if isinstance(codec, str) else codec
    if c is not None and c.name == "fp16" and x.ndim > 0:
        stacked = lax.all_gather(x.astype(jnp.float16), axis_name, axis=0).astype(x.dtype)
        if reduce_fx == "cat":
            return stacked.reshape((-1, *x.shape[1:]))
        return reduce_fx(stacked) if callable(reduce_fx) else stacked
    if c is not None and not c.lossless and hasattr(c, "encode_in_trace") and n > 0 and x.ndim > 0:
        codes, scales = c.encode_in_trace(x)
        stacked_codes = lax.all_gather(codes, axis_name, axis=0)  # (world, padded)
        stacked_scales = lax.all_gather(scales, axis_name, axis=0)  # (world, blocks)
        world = stacked_codes.shape[0]
        stacked = c.decode_in_trace(stacked_codes, stacked_scales, n, x.dtype).reshape((world, *x.shape))
        if reduce_fx == "cat":
            return stacked.reshape((-1, *x.shape[1:]))
        if callable(reduce_fx):
            return reduce_fx(stacked)
        return stacked
    if reduce_fx == "cat":
        return lax.all_gather(x, axis_name, axis=0, tiled=True)
    gathered = lax.all_gather(x, axis_name, axis=0)  # stack: (world, ...)
    return reduce_fx(gathered) if callable(reduce_fx) else gathered
