"""Codec layer: how a state leaf is represented on the wire.

A codec turns one array leaf into one or more *payload* arrays (the bytes a
collective actually moves) plus enough static metadata to invert the mapping.
Three codecs:

- :class:`LosslessCodec` — identity passthrough; the default for everything.
  Bit-identical round trip, wire bytes == raw bytes.
- :class:`Fp16Codec` — fp32 → fp16 cast. Round-trip error ≤ ``2**-11 · |x|``
  for values in fp16 normal range (plus the 2**-24 subnormal quantum near 0).
- :class:`Int8BlockCodec` — EQuARX-style blockwise absmax quantization
  (arxiv 2506.17615): the flat leaf is split into blocks of ``block`` elements,
  each block ships int8 codes plus one fp32 scale (``absmax/127``). Round-trip
  error ≤ ``absmax_block / 254`` per element (round-to-nearest of ``x/scale``),
  asserted in ``tests/comm/test_codec.py``.

Which leaf gets which codec is the :class:`CodecPolicy`'s call — dtype- and
reduction-aware: integer/bool leaves and ``_update_count`` are always lossless
(counts must stay exact), small leaves are not worth the scale overhead, and
reducible fp32 states (``sum``/``mean``/...) stay lossless unless explicitly
opted in — only large float ``cat``/gather states quantize by default.

Host-path ``encode``/``decode`` are numpy (the transport boundary is numpy);
:meth:`Int8BlockCodec.encode_in_trace` / ``decode_in_trace`` are the
jnp twins for quantized in-trace collectives (:func:`metrics_tpu.comm.plane.
reduce_in_trace` with a codec).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "Codec",
    "CodecPolicy",
    "EncodedLeaf",
    "Fp16Codec",
    "Int8BlockCodec",
    "LosslessCodec",
    "get_codec",
    "register_codec",
]


@dataclass
class EncodedLeaf:
    """One leaf's wire representation: payload arrays + inversion metadata."""

    codec: str
    payloads: Tuple[np.ndarray, ...]
    shape: Tuple[int, ...]
    dtype: np.dtype

    @property
    def wire_nbytes(self) -> int:
        return sum(int(p.nbytes) for p in self.payloads)

    @property
    def raw_nbytes(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)) * np.dtype(self.dtype).itemsize


class Codec:
    """Invertible (up to a documented bound) wire representation of one leaf."""

    name = "codec"
    lossless = False

    def encode(self, x: np.ndarray) -> EncodedLeaf:
        raise NotImplementedError

    def decode(self, enc: EncodedLeaf) -> np.ndarray:
        raise NotImplementedError

    def payload_specs(self, shape: Tuple[int, ...], dtype: np.dtype) -> List[Tuple[Tuple[int, ...], np.dtype]]:
        """Static (shape, dtype) of each payload for a leaf of ``shape``/``dtype``.

        Lets the transfer planner lay out coalesced buffers and cache offsets
        without touching data.
        """
        raise NotImplementedError


class LosslessCodec(Codec):
    """Identity passthrough — one payload, the leaf itself."""

    name = "lossless"
    lossless = True

    def encode(self, x: np.ndarray) -> EncodedLeaf:
        x = np.asarray(x)
        return EncodedLeaf(self.name, (x,), tuple(x.shape), x.dtype)

    def decode(self, enc: EncodedLeaf) -> np.ndarray:
        return np.asarray(enc.payloads[0]).reshape(enc.shape).astype(enc.dtype, copy=False)

    def payload_specs(self, shape: Tuple[int, ...], dtype: np.dtype) -> List[Tuple[Tuple[int, ...], np.dtype]]:
        return [(tuple(shape), np.dtype(dtype))]


class Fp16Codec(Codec):
    """Float → fp16 cast. Error ≤ ``2**-11·|x|`` in fp16 normal range."""

    name = "fp16"
    lossless = False

    def encode(self, x: np.ndarray) -> EncodedLeaf:
        x = np.asarray(x)
        return EncodedLeaf(self.name, (x.astype(np.float16),), tuple(x.shape), x.dtype)

    def decode(self, enc: EncodedLeaf) -> np.ndarray:
        return np.asarray(enc.payloads[0]).reshape(enc.shape).astype(enc.dtype, copy=False)

    def payload_specs(self, shape: Tuple[int, ...], dtype: np.dtype) -> List[Tuple[Tuple[int, ...], np.dtype]]:
        return [(tuple(shape), np.dtype(np.float16))]


class Int8BlockCodec(Codec):
    """Blockwise absmax int8: codes (int8, block-padded flat) + scales (fp32/block).

    Per-element round-trip error ≤ ``absmax_block / 254``: with
    ``scale = absmax/127``, round-to-nearest loses at most ``scale/2``.
    All-zero blocks use scale 1 and reconstruct exactly.
    """

    lossless = False

    def __init__(self, block: int = 1024) -> None:
        if block < 1:
            raise ValueError(f"block must be >= 1, got {block}")
        self.block = int(block)
        self.name = f"int8x{self.block}"

    def _padded_len(self, n: int) -> int:
        return ((n + self.block - 1) // self.block) * self.block if n else 0

    def encode(self, x: np.ndarray) -> EncodedLeaf:
        x = np.asarray(x)
        flat = x.astype(np.float32, copy=False).ravel()
        n = flat.size
        padded = self._padded_len(n)
        if padded == 0:
            return EncodedLeaf(
                self.name,
                (np.zeros((0,), np.int8), np.zeros((0,), np.float32)),
                tuple(x.shape),
                x.dtype,
            )
        if padded != n:
            flat = np.concatenate([flat, np.zeros(padded - n, np.float32)])
        blocks = flat.reshape(-1, self.block)
        absmax = np.max(np.abs(blocks), axis=1)
        scales = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
        codes = np.clip(np.rint(blocks / scales[:, None]), -127, 127).astype(np.int8)
        return EncodedLeaf(self.name, (codes.ravel(), scales), tuple(x.shape), x.dtype)

    def decode(self, enc: EncodedLeaf) -> np.ndarray:
        codes, scales = enc.payloads
        n = int(np.prod(enc.shape, dtype=np.int64))
        if n == 0:
            return np.zeros(enc.shape, enc.dtype)
        blocks = np.asarray(codes, np.float32).reshape(-1, self.block) * np.asarray(scales, np.float32)[:, None]
        return blocks.ravel()[:n].reshape(enc.shape).astype(enc.dtype, copy=False)

    def payload_specs(self, shape: Tuple[int, ...], dtype: np.dtype) -> List[Tuple[Tuple[int, ...], np.dtype]]:
        n = int(np.prod(shape, dtype=np.int64))
        padded = self._padded_len(n)
        return [((padded,), np.dtype(np.int8)), ((padded // self.block,), np.dtype(np.float32))]

    # ------------------------------------------------------------ in-trace twins

    def encode_in_trace(self, x: Any) -> Tuple[Any, Any]:
        """jnp version of :meth:`encode` for quantized in-trace collectives.

        Traceable under jit/shard_map (shapes static). Returns ``(codes, scales)``
        with codes still flat-per-block — the caller gathers both and calls
        :meth:`decode_in_trace`.
        """
        import jax.numpy as jnp

        flat = jnp.ravel(x).astype(jnp.float32)
        n = flat.size
        padded = self._padded_len(int(n))
        if padded != n:
            flat = jnp.concatenate([flat, jnp.zeros(padded - n, jnp.float32)])
        blocks = flat.reshape(-1, self.block)
        absmax = jnp.max(jnp.abs(blocks), axis=1)
        scales = jnp.where(absmax > 0, absmax / 127.0, 1.0)
        codes = jnp.clip(jnp.round(blocks / scales[:, None]), -127, 127).astype(jnp.int8)
        return codes.reshape(-1), scales

    def decode_in_trace(self, codes: Any, scales: Any, n: int, target_dtype: Any) -> Any:
        """Invert :meth:`encode_in_trace` back to flat length-``n`` trailing axis.

        Batch-aware: leading axes (e.g. the world axis of an all-gather) pass
        through — ``(..., padded)`` codes and ``(..., blocks)`` scales decode to
        ``(..., n)``.
        """
        import jax.numpy as jnp

        blocks = codes.astype(jnp.float32).reshape(*codes.shape[:-1], -1, self.block) * scales[..., None]
        return blocks.reshape(*codes.shape[:-1], -1)[..., :n].astype(target_dtype)


_CODECS: Dict[str, Codec] = {}


def register_codec(codec: Codec) -> Codec:
    """Add a codec to the by-name registry (used by policies and plans)."""
    _CODECS[codec.name] = codec
    return codec


register_codec(LosslessCodec())
register_codec(Fp16Codec())
register_codec(Int8BlockCodec())  # int8x1024, the default lossy codec
# aliases so policies can say "int8"/"fp16" without knowing the block size
_CODECS["int8"] = _CODECS["int8x1024"]


def get_codec(name: str) -> Codec:
    try:
        return _CODECS[name]
    except KeyError:
        raise KeyError(f"unknown codec {name!r}; registered: {sorted(_CODECS)}") from None


_REDUCIBLE = ("sum", "mean", "max", "min")


@dataclass(frozen=True)
class CodecPolicy:
    """Per-leaf codec choice, dtype- and reduction-aware.

    ``lossy=None`` (the default) keeps every leaf lossless — the comm plane is
    then bit-identical to the pre-comm sync. With ``lossy="int8"`` (or
    ``"fp16"``), large floating-point gather-style leaves (``cat``/``None``/
    callable reductions) quantize; counts, integer/bool dtypes,
    ``_update_count`` and anything under ``min_bytes`` stay lossless, and
    reducible float states only quantize when ``quantize_reducible=True``.
    """

    lossy: Optional[str] = None
    min_bytes: int = 4096
    quantize_reducible: bool = False

    def choose(self, name: str, reduction: Any, dtype: Any, nbytes: int) -> str:
        if self.lossy is None:
            return "lossless"
        if name == "_update_count":
            return "lossless"
        kind = np.dtype(dtype).kind
        if kind not in ("f", "c") or np.dtype(dtype).itemsize < 4:
            return "lossless"  # ints/bools/already-half: exactness beats bytes
        if nbytes < self.min_bytes:
            return "lossless"
        if isinstance(reduction, str) and reduction in _REDUCIBLE and not self.quantize_reducible:
            return "lossless"
        return self.lossy

    def all_lossless(self) -> "CodecPolicy":
        """The degradation-ladder step-1 variant of this policy."""
        return replace(self, lossy=None)
