"""Host transports: who moves the wire buffers, and what happens when they don't.

A :class:`Transport` is the buffer-level boundary of the comm plane: it moves
numpy arrays between processes and knows nothing about metric states, codecs,
or plans. The contract is the classic same-shape ``allgather`` (every rank
passes an identically-shaped array, gets back the per-rank list in rank order);
transports that can also do per-rank exact-size ``broadcast_from`` advertise it
with ``supports_broadcast`` so :func:`gather_ragged` can skip pad-to-max when
padding would dominate the wire.

Concrete transports:

- :class:`LocalTransport` — world 1, identity. The single-process default.
- :class:`MultihostTransport` — ``jax.experimental.multihost_utils`` over a
  real multi-controller job (``process_allgather`` / ``broadcast_one_to_all``).
- :class:`LoopbackWorld` — an in-process N-rank world over threads + barriers,
  for protocol tests and fault rehearsal without a cluster.
- :class:`ReplicaFakeTransport` / :class:`ScriptedFakeTransport` — single-caller
  fakes: every peer mirrors the caller, or replies are scripted per call.
- :class:`FlakyTransport` / :class:`StallTransport` / :class:`DeadPeerTransport`
  — fault injectors wrapping any inner transport, for exercising the retry →
  degradation ladder (Prime PCCL's failure taxonomy, arxiv 2505.14065).

Failure vocabulary: :class:`TransportError` (transient collective failure),
:class:`TransportTimeout` (a peer stalled past the deadline),
:class:`PeerLostError` (membership broke — retrying the same world cannot
succeed). The plane's ladder treats them uniformly except that a lost peer
skips straight past same-step retries.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

__all__ = [
    "DeadPeerTransport",
    "FlakyTransport",
    "LocalTransport",
    "LoopbackWorld",
    "MultihostTransport",
    "PeerLostError",
    "ReplicaFakeTransport",
    "ScriptedFakeTransport",
    "StallTransport",
    "Transport",
    "TransportError",
    "TransportTimeout",
    "gather_ragged",
]


class TransportError(RuntimeError):
    """A collective failed for a reason worth retrying (transient fabric/peer hiccup)."""


class TransportTimeout(TransportError):
    """A peer stalled past the configured deadline."""


class PeerLostError(TransportError):
    """Membership degraded — a peer is gone; retrying the same world cannot succeed."""


class Transport:
    """Buffer-level collective boundary. Same-shape allgather is the one requirement."""

    name = "transport"
    supports_broadcast = False

    def world_size(self) -> int:
        raise NotImplementedError

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        """Every rank passes an identically-shaped array; returns rank-ordered rows."""
        raise NotImplementedError

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        """Root's exact-size array to every rank (non-roots pass ``x=None``)."""
        raise NotImplementedError(f"{self.name} does not support broadcast_from")


class LocalTransport(Transport):
    """World of one — every collective is the identity."""

    name = "local"
    supports_broadcast = True

    def world_size(self) -> int:
        return 1

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        return [np.asarray(x)]

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        assert root == 0 and x is not None
        return np.asarray(x)


class MultihostTransport(Transport):
    """The real thing: multi-controller JAX via ``multihost_utils``."""

    name = "multihost"
    supports_broadcast = True

    def world_size(self) -> int:
        import jax

        return jax.process_count()

    @property
    def rank(self) -> int:
        import jax

        return jax.process_index()

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        from jax.experimental import multihost_utils

        gathered = np.asarray(multihost_utils.process_allgather(np.asarray(x)))
        return [gathered[i] for i in range(self.world_size())]

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        import jax
        from jax.experimental import multihost_utils

        is_source = jax.process_index() == root
        payload = np.asarray(x) if is_source else np.zeros(tuple(shape), np.dtype(dtype))
        return np.asarray(multihost_utils.broadcast_one_to_all(payload, is_source=is_source))


# --------------------------------------------------------------------- loopback world


class LoopbackWorld:
    """An in-process N-rank world: one transport per simulated rank, matched up
    with barriers, so the *real* wire protocols (pad-to-max, exact broadcast,
    plan execution) run end to end without a cluster.

    Every rank must make the same sequence of collective calls; a rank that
    falls behind past ``timeout`` breaks the barrier and every participant
    raises :class:`TransportTimeout` instead of deadlocking.
    """

    def __init__(self, world: int, timeout: float = 30.0) -> None:
        if world < 1:
            raise ValueError("world must be >= 1")
        self.world = world
        self.timeout = timeout
        self._deposit_barrier = threading.Barrier(world)
        self._read_barrier = threading.Barrier(world)
        self._slots: List[Optional[np.ndarray]] = [None] * world

    def transport(self, rank: int) -> "_LoopbackTransport":
        if not 0 <= rank < self.world:
            raise ValueError(f"rank {rank} outside world {self.world}")
        return _LoopbackTransport(self, rank)

    def run(self, fns: Sequence[Callable[["_LoopbackTransport"], Any]]) -> List[Any]:
        """Run one callable per rank (each given its transport); returns results
        in rank order, re-raising the first per-rank exception."""
        if len(fns) != self.world:
            raise ValueError(f"need exactly {self.world} rank fns, got {len(fns)}")
        results: List[Any] = [None] * self.world
        errors: List[Optional[BaseException]] = [None] * self.world

        def _runner(rank: int) -> None:
            try:
                results[rank] = fns[rank](self.transport(rank))
            except BaseException as exc:  # noqa: BLE001 — propagated to the caller below
                errors[rank] = exc
                self._deposit_barrier.abort()
                self._read_barrier.abort()

        threads = [threading.Thread(target=_runner, args=(r,), daemon=True) for r in range(self.world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.timeout * 4)
        for exc in errors:
            if exc is not None:
                raise exc
        return results

    def _exchange(self, rank: int, x: Optional[np.ndarray]) -> List[Optional[np.ndarray]]:
        self._slots[rank] = None if x is None else np.asarray(x)
        try:
            self._deposit_barrier.wait(self.timeout)
            out = list(self._slots)
            self._read_barrier.wait(self.timeout)
        except threading.BrokenBarrierError:
            self._deposit_barrier.abort()
            self._read_barrier.abort()
            raise TransportTimeout(f"loopback rank {rank}: a peer stalled or died mid-collective") from None
        return out


class _LoopbackTransport(Transport):
    name = "loopback"
    supports_broadcast = True

    def __init__(self, world: LoopbackWorld, rank: int) -> None:
        self._world = world
        self.rank = rank

    def world_size(self) -> int:
        return self._world.world

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        rows = self._world._exchange(self.rank, np.asarray(x))
        if any(r is None for r in rows):
            raise TransportError(f"loopback rank {self.rank}: a peer deposited nothing")
        return [np.asarray(r) for r in rows]

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        rows = self._world._exchange(self.rank, x if self.rank == root else None)
        got = rows[root]
        if got is None:
            raise TransportError(f"loopback rank {self.rank}: root {root} deposited nothing")
        return np.asarray(got)


# --------------------------------------------------------------------- test fakes


class ReplicaFakeTransport(Transport):
    """Every peer mirrors the caller — the cheapest way to fake world=N when
    per-rank contents don't matter (sum → N·x, cat → x repeated N times)."""

    name = "replica_fake"
    supports_broadcast = True

    def __init__(self, world: int) -> None:
        self._world = int(world)
        self.calls = 0

    def world_size(self) -> int:
        return self._world

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        self.calls += 1
        x = np.asarray(x)
        return [x.copy() for _ in range(self._world)]

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        self.calls += 1
        assert x is not None  # with mirrored peers the caller is every root
        return np.asarray(x)


class ScriptedFakeTransport(Transport):
    """Replies scripted per call: ``script[i]`` is the rank-ordered row list the
    i-th allgather returns (the caller's own row replaced by its live buffer)."""

    name = "scripted_fake"

    def __init__(self, world: int, script: Sequence[Sequence[np.ndarray]], rank: int = 0) -> None:
        self._world = int(world)
        self._script = [list(rows) for rows in script]
        self._rank = rank
        self.calls = 0

    def world_size(self) -> int:
        return self._world

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        if self.calls >= len(self._script):
            raise TransportError(f"scripted transport exhausted after {len(self._script)} calls")
        rows = [np.asarray(r) for r in self._script[self.calls]]
        rows[self._rank] = np.asarray(x)
        self.calls += 1
        return rows


class FlakyTransport(Transport):
    """Raise on the first ``fail`` collective calls, then delegate — the
    transient-failure injector for retry tests."""

    name = "flaky"

    def __init__(self, inner: Transport, fail: int = 1, exc: Callable[[], Exception] = TransportError) -> None:
        self._inner = inner
        self._remaining = int(fail)
        self._exc = exc
        self.failures_injected = 0

    @property
    def supports_broadcast(self) -> bool:  # type: ignore[override]
        return self._inner.supports_broadcast

    @property
    def rank(self) -> Optional[int]:
        return getattr(self._inner, "rank", None)

    def world_size(self) -> int:
        return self._inner.world_size()

    def _maybe_fail(self) -> None:
        if self._remaining > 0:
            self._remaining -= 1
            self.failures_injected += 1
            raise self._exc()

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        self._maybe_fail()
        return self._inner.allgather(x)

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        self._maybe_fail()
        return self._inner.broadcast_from(x, root, shape, dtype)


class StallTransport(Transport):
    """Sleep ``stall_s`` before the first ``stalls`` collectives complete — what a
    wedged peer looks like to the plane's deadline."""

    name = "stall"

    def __init__(self, inner: Transport, stall_s: float, stalls: int = 1) -> None:
        self._inner = inner
        self._stall_s = stall_s
        self._remaining = int(stalls)

    @property
    def supports_broadcast(self) -> bool:  # type: ignore[override]
        return self._inner.supports_broadcast

    @property
    def rank(self) -> Optional[int]:
        return getattr(self._inner, "rank", None)

    def world_size(self) -> int:
        return self._inner.world_size()

    def _maybe_stall(self) -> None:
        if self._remaining > 0:
            self._remaining -= 1
            time.sleep(self._stall_s)

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        self._maybe_stall()
        return self._inner.allgather(x)

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        self._maybe_stall()
        return self._inner.broadcast_from(x, root, shape, dtype)


class DeadPeerTransport(Transport):
    """Every collective fails with :class:`PeerLostError` — the bottom of the
    ladder: membership is broken and only local state remains."""

    name = "dead_peer"

    def __init__(self, world: int = 2) -> None:
        self._world = world

    def world_size(self) -> int:
        return self._world

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        raise PeerLostError("peer left the membership")

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        raise PeerLostError("peer left the membership")


# --------------------------------------------------------------------- ragged gather


def _shape_vector(x: np.ndarray) -> np.ndarray:
    return np.asarray(x.shape, dtype=np.int64) if x.ndim else np.zeros((0,), np.int64)


def gather_ragged(
    transport: Transport,
    x: np.ndarray,
    *,
    rank: Optional[int] = None,
    max_pad_ratio: float = 1.25,
) -> List[np.ndarray]:
    """Gather a possibly-ragged array from every rank, in rank order.

    The reference protocol (torchmetrics ``gather_all_tensors``): gather shape
    vectors first; equal shapes → one allgather; unequal → pad to the
    elementwise max along every dim, gather, trim each rank back. Mixed ranks
    (different ``ndim``) are a protocol error, as in the reference.

    When the transport supports exact-size broadcast and pad-to-max would ship
    more than ``max_pad_ratio``× the real payload, each rank broadcasts its
    exact buffer instead — W rounds, zero pad bytes; the transfer planner leans
    on this for heavily skewed ``cat`` states.
    """
    x = np.asarray(x)
    world = transport.world_size()
    if world == 1:
        return [x]
    shapes = transport.allgather(_shape_vector(x))
    if any(s.shape != shapes[0].shape for s in shapes):
        ranks = sorted({int(s.size) for s in shapes})
        raise ValueError(
            f"gather_ragged: mixed-rank shards (ndims {ranks}); the pad-to-max protocol "
            "requires every process to contribute the same number of dimensions"
        )
    all_shapes = [tuple(int(d) for d in s) for s in shapes]
    if all(s == all_shapes[0] for s in all_shapes):
        return transport.allgather(x)
    max_shape = tuple(max(s[d] for s in all_shapes) for d in range(len(all_shapes[0])))
    total = sum(int(np.prod(s, dtype=np.int64)) for s in all_shapes)
    padded_total = world * int(np.prod(max_shape, dtype=np.int64))
    if rank is None:
        rank = getattr(transport, "rank", None)
    # exact-size broadcast needs to know which rank WE are (the root must pass
    # its live buffer); without that, pad-to-max is the only correct protocol
    if transport.supports_broadcast and rank is not None and total > 0 and padded_total > max_pad_ratio * total:
        out = []
        for r in range(world):
            mine = r == rank
            out.append(transport.broadcast_from(x if mine else None, r, all_shapes[r], x.dtype))
        return out
    pad = [(0, m - s) for m, s in zip(max_shape, x.shape)]
    padded = np.pad(x, pad)
    gathered = transport.allgather(padded)
    return [np.asarray(gathered[i])[tuple(slice(0, d) for d in all_shapes[i])] for i in range(world)]
