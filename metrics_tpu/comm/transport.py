"""Host transports: who moves the wire buffers, and what happens when they don't.

A :class:`Transport` is the buffer-level boundary of the comm plane: it moves
numpy arrays between processes and knows nothing about metric states, codecs,
or plans. The contract is the classic same-shape ``allgather`` (every rank
passes an identically-shaped array, gets back the per-rank list in rank order);
transports that can also do per-rank exact-size ``broadcast_from`` advertise it
with ``supports_broadcast`` so :func:`gather_ragged` can skip pad-to-max when
padding would dominate the wire.

Concrete transports:

- :class:`LocalTransport` — world 1, identity. The single-process default.
- :class:`MultihostTransport` — ``jax.experimental.multihost_utils`` over a
  real multi-controller job (``process_allgather`` / ``broadcast_one_to_all``).
- :class:`LoopbackWorld` — an in-process N-rank world over threads + barriers,
  for protocol tests and fault rehearsal without a cluster.
- :class:`ReplicaFakeTransport` / :class:`ScriptedFakeTransport` — single-caller
  fakes: every peer mirrors the caller, or replies are scripted per call.
- :class:`FlakyTransport` / :class:`StallTransport` / :class:`DeadPeerTransport`
  — fault injectors wrapping any inner transport, for exercising the retry →
  degradation ladder (Prime PCCL's failure taxonomy, arxiv 2505.14065).

Failure vocabulary: :class:`TransportError` (transient collective failure),
:class:`TransportTimeout` (a peer stalled past the deadline),
:class:`PeerLostError` (membership broke — retrying the same world cannot
succeed; carries the *attributed* straggler ranks when the transport knows
them). The plane's ladder treats them uniformly except that a lost peer
skips straight past same-step retries.

Membership-capable transports (``supports_membership = True``) additionally
expose the primitives :mod:`metrics_tpu.comm.membership` builds its two-phase
live-set agreement on: ``membership_exchange`` (a deadlined, watermarked
deposit/collect board that cannot deadlock on dead peers), ``subset(ranks)``
(a transport over an agreed sub-world), and ``reset()`` (repair a world whose
barriers an aborted round broke). :class:`LoopbackWorld` implements all three;
the real :class:`MultihostTransport` does not (agreement over a multi-controller
job needs an out-of-band store), so the plane's ``live_subset`` rung simply
does not engage there.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "DeadPeerTransport",
    "FlakyTransport",
    "LocalTransport",
    "LoopbackWorld",
    "MultihostTransport",
    "PeerLostError",
    "ReplicaFakeTransport",
    "ScriptedFakeTransport",
    "StallTransport",
    "Transport",
    "TransportError",
    "TransportTimeout",
    "gather_ragged",
]


class TransportError(RuntimeError):
    """A collective failed for a reason worth retrying (transient fabric/peer hiccup)."""


class TransportTimeout(TransportError):
    """A peer stalled past the configured deadline."""


class PeerLostError(TransportError):
    """Membership degraded — a peer is gone; retrying the same world cannot succeed.

    ``peers`` carries the attributed straggler/dead ranks when the transport can
    name them (empty when it can't) — the membership layer's suspicion counters
    feed on exactly this attribution.
    """

    def __init__(self, message: str = "peer left the membership", peers: Sequence[int] = ()) -> None:
        super().__init__(message)
        self.peers: Tuple[int, ...] = tuple(sorted(int(p) for p in peers))


class Transport:
    """Buffer-level collective boundary. Same-shape allgather is the one requirement."""

    name = "transport"
    supports_broadcast = False
    supports_membership = False

    def world_size(self) -> int:
        raise NotImplementedError

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        """Every rank passes an identically-shaped array; returns rank-ordered rows."""
        raise NotImplementedError

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        """Root's exact-size array to every rank (non-roots pass ``x=None``)."""
        raise NotImplementedError(f"{self.name} does not support broadcast_from")


class LocalTransport(Transport):
    """World of one — every collective is the identity."""

    name = "local"
    supports_broadcast = True

    def world_size(self) -> int:
        return 1

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        return [np.asarray(x)]

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        assert root == 0 and x is not None
        return np.asarray(x)


class MultihostTransport(Transport):
    """The real thing: multi-controller JAX via ``multihost_utils``."""

    name = "multihost"
    supports_broadcast = True

    def world_size(self) -> int:
        import jax

        return jax.process_count()

    @property
    def rank(self) -> int:
        import jax

        return jax.process_index()

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        from jax.experimental import multihost_utils

        gathered = np.asarray(multihost_utils.process_allgather(np.asarray(x)))
        return [gathered[i] for i in range(self.world_size())]

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        import jax
        from jax.experimental import multihost_utils

        is_source = jax.process_index() == root
        payload = np.asarray(x) if is_source else np.zeros(tuple(shape), np.dtype(dtype))
        return np.asarray(multihost_utils.broadcast_one_to_all(payload, is_source=is_source))


# --------------------------------------------------------------- call cancellation

# Cooperative abandonment channel for deadlined collectives: the plane's
# deadline wrapper runs each collective in a worker thread and, on timeout,
# sets the worker's cancel event. A real multihost collective cannot observe
# it (no abort exists), but the in-process transports check it before touching
# shared barriers — so a late-completing abandoned call can never deposit into
# a fresh attempt's round.
_CALL_CANCEL = threading.local()


def set_call_cancel_event(event: Optional[threading.Event]) -> None:
    """Install (or clear) the current thread's collective-cancel event."""
    _CALL_CANCEL.event = event


def current_call_cancelled() -> bool:
    event = getattr(_CALL_CANCEL, "event", None)
    return event is not None and event.is_set()


# --------------------------------------------------------------------- loopback world


class LoopbackWorld:
    """An in-process N-rank world: one transport per simulated rank, matched up
    with barriers, so the *real* wire protocols (pad-to-max, exact broadcast,
    plan execution) run end to end without a cluster.

    Every rank must make the same sequence of collective calls; a rank that
    falls behind past ``timeout`` breaks the barrier and every participant
    raises an *attributed* :class:`PeerLostError` naming the rank(s) that fell
    behind (or :class:`TransportTimeout` when no straggler can be named)
    instead of deadlocking. :meth:`reset` repairs the broken barriers so the
    world survives an aborted round, and the world carries the membership
    primitives (deposit board, sub-world groups) the agreement protocol needs.
    """

    def __init__(self, world: int, timeout: float = 30.0) -> None:
        if world < 1:
            raise ValueError("world must be >= 1")
        self.world = world
        self.timeout = timeout
        self._deposit_barrier = threading.Barrier(world)
        self._read_barrier = threading.Barrier(world)
        self._slots: List[Optional[np.ndarray]] = [None] * world
        # monotonic per-rank collective-entry counters: after a barrier abort,
        # the ranks with strictly fewer arrivals than the observer are the ones
        # that never showed up — that's the straggler attribution
        self._arrivals = [0] * world
        self._generation = 0
        self._state_lock = threading.Lock()
        # membership board: phase -> per-rank (seq, payload) cells, under one
        # condition; seq is a global monotonic stamp so readers can tell a
        # fresh deposit from last round's leftovers via per-reader watermarks
        self._mb_cond = threading.Condition()
        self._mb_seq = 0
        self._mb_cells: Dict[str, List[Optional[Tuple[int, Any]]]] = {}
        self._subgroups: Dict[Tuple[int, ...], "_SubGroup"] = {}

    def reset(self) -> None:
        """Repair the world after an aborted or abandoned round.

        Both barriers are reset unconditionally (kicking any abandoned waiter a
        deadline-expired collective left behind — it raises instead of
        occupying a barrier seat in the next round), slots are cleared, and the
        world generation is bumped so an exchange that straddles the reset
        fails loudly instead of pairing with the next round's deposits.
        """
        with self._state_lock:
            self._generation += 1
            self._deposit_barrier.reset()
            self._read_barrier.reset()
            self._slots = [None] * self.world
            groups = list(self._subgroups.values())
        for g in groups:
            g.repair()

    # ---------------------------------------------------------- membership board

    def deposit_membership(self, rank: int, phase: str, payload: Any) -> int:
        with self._mb_cond:
            self._mb_seq += 1
            cells = self._mb_cells.setdefault(phase, [None] * self.world)
            cells[rank] = (self._mb_seq, payload)
            self._mb_cond.notify_all()
            return self._mb_seq

    def collect_membership(
        self,
        rank: int,
        phase: str,
        expected: Sequence[int],
        deadline_s: float,
        watermarks: Dict[int, int],
        grace_s: float = 0.0,
    ) -> Dict[int, Tuple[int, Any]]:
        """Wait until every ``expected`` rank has a deposit fresher than its
        watermark (holding a further ``grace_s`` for opportunistic deposits from
        ranks *outside* ``expected`` — that is how rejoiners get noticed), or
        ``deadline_s`` expires; return every fresh deposit seen, by rank."""
        start = time.monotonic()
        deadline = start + deadline_s
        grace_end = start + min(grace_s, deadline_s)
        expected = [int(r) for r in expected]
        with self._mb_cond:
            while True:
                cells = self._mb_cells.get(phase) or []
                fresh = {
                    r: cell
                    for r, cell in enumerate(cells)
                    if cell is not None and cell[0] > watermarks.get(r, -1)
                }
                now = time.monotonic()
                have_expected = all(r in fresh or r == rank for r in expected)
                if have_expected and now >= grace_end:
                    return fresh
                if now >= deadline:
                    return fresh
                horizon = grace_end if have_expected else deadline
                self._mb_cond.wait(timeout=max(1e-4, horizon - now))

    # ---------------------------------------------------------- sub-world groups

    def subgroup(self, members: Tuple[int, ...]) -> "_SubGroup":
        members = tuple(sorted(int(m) for m in members))
        if not members or any(not 0 <= m < self.world for m in members):
            raise ValueError(f"subgroup members {members} outside world {self.world}")
        with self._state_lock:
            group = self._subgroups.get(members)
            if group is None:
                group = _SubGroup(self, members)
                self._subgroups[members] = group
            return group

    def transport(self, rank: int) -> "_LoopbackTransport":
        if not 0 <= rank < self.world:
            raise ValueError(f"rank {rank} outside world {self.world}")
        return _LoopbackTransport(self, rank)

    def run(self, fns: Sequence[Callable[["_LoopbackTransport"], Any]]) -> List[Any]:
        """Run one callable per rank (each given its transport); returns results
        in rank order, re-raising the first per-rank exception."""
        if len(fns) != self.world:
            raise ValueError(f"need exactly {self.world} rank fns, got {len(fns)}")
        results: List[Any] = [None] * self.world
        errors: List[Optional[BaseException]] = [None] * self.world

        def _runner(rank: int) -> None:
            try:
                results[rank] = fns[rank](self.transport(rank))
            except BaseException as exc:  # noqa: BLE001 — propagated to the caller below
                errors[rank] = exc
                self._deposit_barrier.abort()
                self._read_barrier.abort()

        threads = [threading.Thread(target=_runner, args=(r,), daemon=True) for r in range(self.world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.timeout * 4)
        for exc in errors:
            if exc is not None:
                raise exc
        return results

    def _exchange(self, rank: int, x: Optional[np.ndarray]) -> List[Optional[np.ndarray]]:
        if current_call_cancelled():
            raise TransportError(f"loopback rank {rank}: abandoned deadline-expired collective discarded")
        with self._state_lock:
            self._arrivals[rank] += 1
            gen = self._generation
        self._slots[rank] = None if x is None else np.asarray(x)
        try:
            self._deposit_barrier.wait(self.timeout)
            out = list(self._slots)
            self._read_barrier.wait(self.timeout)
        except threading.BrokenBarrierError:
            with self._state_lock:
                same_gen = self._generation == gen
                me = self._arrivals[rank]
                stragglers = [r for r in range(self.world) if r != rank and self._arrivals[r] < me]
            if same_gen:
                # only break the round we were actually part of — if a reset
                # already repaired the world, the fresh barriers stay usable
                self._deposit_barrier.abort()
                self._read_barrier.abort()
            if stragglers:
                raise PeerLostError(
                    f"loopback rank {rank}: peers {stragglers} fell behind mid-collective",
                    peers=stragglers,
                ) from None
            raise TransportTimeout(f"loopback rank {rank}: a peer stalled or died mid-collective") from None
        if self._generation != gen:
            raise TransportError(f"loopback rank {rank}: world reset mid-collective (stale exchange discarded)")
        return out


class _SubGroup:
    """A sub-world of a :class:`LoopbackWorld`: its own barrier pair and slots
    over a fixed member tuple, so an agreed live subset can run the real wire
    protocols without the dead ranks' barrier seats. Cached per member tuple on
    the parent world — every survivor computes the same agreed set, so every
    survivor lands on the same group object."""

    def __init__(self, world: LoopbackWorld, members: Tuple[int, ...]) -> None:
        self.members = members
        self.timeout = world.timeout
        self._index = {g: i for i, g in enumerate(members)}
        n = len(members)
        self._deposit_barrier = threading.Barrier(n)
        self._read_barrier = threading.Barrier(n)
        self._slots: List[Optional[np.ndarray]] = [None] * n
        self._arrivals = [0] * n
        self._lock = threading.Lock()

    def repair(self) -> None:
        with self._lock:
            self._deposit_barrier.reset()
            self._read_barrier.reset()
            self._slots = [None] * len(self.members)

    def transport(self, global_rank: int) -> "_LoopbackSubTransport":
        if global_rank not in self._index:
            raise ValueError(f"rank {global_rank} is not a member of subgroup {self.members}")
        return _LoopbackSubTransport(self, global_rank)

    def _exchange(self, idx: int, x: Optional[np.ndarray]) -> List[Optional[np.ndarray]]:
        if current_call_cancelled():
            raise TransportError(
                f"loopback subgroup {self.members}: abandoned deadline-expired collective discarded"
            )
        with self._lock:
            self._arrivals[idx] += 1
        self._slots[idx] = None if x is None else np.asarray(x)
        try:
            self._deposit_barrier.wait(self.timeout)
            out = list(self._slots)
            self._read_barrier.wait(self.timeout)
        except threading.BrokenBarrierError:
            self._deposit_barrier.abort()
            self._read_barrier.abort()
            with self._lock:
                me = self._arrivals[idx]
                stragglers = [self.members[i] for i in range(len(self.members)) if i != idx and self._arrivals[i] < me]
            if stragglers:
                raise PeerLostError(
                    f"loopback subgroup {self.members}: peers {stragglers} fell behind mid-collective",
                    peers=stragglers,
                ) from None
            raise TransportTimeout(
                f"loopback subgroup {self.members}: a peer stalled or died mid-collective"
            ) from None
        return out


class _LoopbackSubTransport(Transport):
    """Transport over an agreed sub-world: global ranks map to dense subset
    indices, ``world_size()`` is the subset size, and plan execution runs
    unchanged (plans are laid out against ``transport.world_size()``)."""

    name = "loopback_subset"
    supports_broadcast = True

    def __init__(self, group: _SubGroup, global_rank: int) -> None:
        self._group = group
        self.global_rank = global_rank
        self.rank = group._index[global_rank]  # subset index: what plan roots mean

    @property
    def members(self) -> Tuple[int, ...]:
        return self._group.members

    def reset(self) -> None:
        self._group.repair()

    def world_size(self) -> int:
        return len(self._group.members)

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        rows = self._group._exchange(self.rank, np.asarray(x))
        if any(r is None for r in rows):
            raise TransportError(f"loopback subgroup {self.members}: a peer deposited nothing")
        return [np.asarray(r) for r in rows]

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        rows = self._group._exchange(self.rank, x if self.rank == root else None)
        got = rows[root]
        if got is None:
            raise TransportError(f"loopback subgroup {self.members}: root {root} deposited nothing")
        return np.asarray(got)


class _LoopbackTransport(Transport):
    name = "loopback"
    supports_broadcast = True
    supports_membership = True

    def __init__(self, world: LoopbackWorld, rank: int) -> None:
        self._world = world
        self.rank = rank

    def world_size(self) -> int:
        return self._world.world

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        rows = self._world._exchange(self.rank, np.asarray(x))
        if any(r is None for r in rows):
            raise TransportError(f"loopback rank {self.rank}: a peer deposited nothing")
        return [np.asarray(r) for r in rows]

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        rows = self._world._exchange(self.rank, x if self.rank == root else None)
        got = rows[root]
        if got is None:
            raise TransportError(f"loopback rank {self.rank}: root {root} deposited nothing")
        return np.asarray(got)

    # ------------------------------------------------------ membership primitives

    def reset(self) -> None:
        self._world.reset()

    def membership_exchange(
        self,
        phase: str,
        payload: Any,
        *,
        deadline_s: float,
        expected: Sequence[int],
        watermarks: Dict[int, int],
        grace_s: float = 0.0,
    ) -> Dict[int, Tuple[int, Any]]:
        """Deposit ``payload`` on the world's membership board under ``phase``
        and collect every fresh deposit (see ``collect_membership``). Bounded by
        ``deadline_s`` — a dead peer costs the deadline, never a deadlock."""
        self._world.deposit_membership(self.rank, phase, payload)
        return self._world.collect_membership(self.rank, phase, expected, deadline_s, watermarks, grace_s)

    def subset(self, ranks: Sequence[int]) -> Transport:
        members = tuple(sorted(int(r) for r in ranks))
        if members == tuple(range(self._world.world)):
            return self
        return self._world.subgroup(members).transport(self.rank)


# --------------------------------------------------------------------- test fakes


class ReplicaFakeTransport(Transport):
    """Every peer mirrors the caller — the cheapest way to fake world=N when
    per-rank contents don't matter (sum → N·x, cat → x repeated N times)."""

    name = "replica_fake"
    supports_broadcast = True

    def __init__(self, world: int) -> None:
        self._world = int(world)
        self.calls = 0

    def world_size(self) -> int:
        return self._world

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        self.calls += 1
        x = np.asarray(x)
        return [x.copy() for _ in range(self._world)]

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        self.calls += 1
        assert x is not None  # with mirrored peers the caller is every root
        return np.asarray(x)


class ScriptedFakeTransport(Transport):
    """Replies scripted per call: ``script[i]`` is the rank-ordered row list the
    i-th allgather returns (the caller's own row replaced by its live buffer)."""

    name = "scripted_fake"

    def __init__(self, world: int, script: Sequence[Sequence[np.ndarray]], rank: int = 0) -> None:
        self._world = int(world)
        self._script = [list(rows) for rows in script]
        self._rank = rank
        self.calls = 0

    def world_size(self) -> int:
        return self._world

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        if self.calls >= len(self._script):
            raise TransportError(f"scripted transport exhausted after {len(self._script)} calls")
        rows = [np.asarray(r) for r in self._script[self.calls]]
        rows[self._rank] = np.asarray(x)
        self.calls += 1
        return rows


class _MembershipPassthrough:
    """Mixin for wrappers: forward the membership primitives to the wrapped
    transport so fault injection composes with the agreement protocol."""

    _inner: Transport

    @property
    def supports_membership(self) -> bool:  # type: ignore[override]
        return getattr(self._inner, "supports_membership", False)

    def reset(self) -> None:
        reset = getattr(self._inner, "reset", None)
        if reset is not None:
            reset()

    def membership_exchange(self, phase: str, payload: Any, **kwargs: Any) -> Dict[int, Tuple[int, Any]]:
        return self._inner.membership_exchange(phase, payload, **kwargs)  # type: ignore[attr-defined]

    def subset(self, ranks: Sequence[int]) -> Transport:
        return self._inner.subset(ranks)  # type: ignore[attr-defined]


class FlakyTransport(_MembershipPassthrough, Transport):
    """Raise on the first ``fail`` collective calls, then delegate — the
    transient-failure injector for retry tests."""

    name = "flaky"

    def __init__(self, inner: Transport, fail: int = 1, exc: Callable[[], Exception] = TransportError) -> None:
        self._inner = inner
        self._remaining = int(fail)
        self._exc = exc
        self.failures_injected = 0

    @property
    def supports_broadcast(self) -> bool:  # type: ignore[override]
        return self._inner.supports_broadcast

    @property
    def rank(self) -> Optional[int]:
        return getattr(self._inner, "rank", None)

    def world_size(self) -> int:
        return self._inner.world_size()

    def _maybe_fail(self) -> None:
        if self._remaining > 0:
            self._remaining -= 1
            self.failures_injected += 1
            raise self._exc()

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        self._maybe_fail()
        return self._inner.allgather(x)

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        self._maybe_fail()
        return self._inner.broadcast_from(x, root, shape, dtype)


class StallTransport(_MembershipPassthrough, Transport):
    """Sleep ``stall_s`` before the first ``stalls`` collectives complete — what a
    wedged peer looks like to the plane's deadline. The stalled collective DOES
    eventually run against the inner transport, which is exactly the
    late-completion hazard the plane's generation-stamped deadline wrapper must
    survive."""

    name = "stall"

    def __init__(self, inner: Transport, stall_s: float, stalls: int = 1) -> None:
        self._inner = inner
        self._stall_s = stall_s
        self._remaining = int(stalls)

    @property
    def supports_broadcast(self) -> bool:  # type: ignore[override]
        return self._inner.supports_broadcast

    @property
    def rank(self) -> Optional[int]:
        return getattr(self._inner, "rank", None)

    def world_size(self) -> int:
        return self._inner.world_size()

    def _maybe_stall(self) -> None:
        if self._remaining > 0:
            self._remaining -= 1
            time.sleep(self._stall_s)

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        self._maybe_stall()
        return self._inner.allgather(x)

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        self._maybe_stall()
        return self._inner.broadcast_from(x, root, shape, dtype)


class DeadPeerTransport(Transport):
    """Every collective fails with :class:`PeerLostError` — the bottom of the
    ladder: membership is broken and only local state remains."""

    name = "dead_peer"

    def __init__(self, world: int = 2) -> None:
        self._world = world

    def world_size(self) -> int:
        return self._world

    def allgather(self, x: np.ndarray) -> List[np.ndarray]:
        raise PeerLostError("peer left the membership")

    def broadcast_from(self, x: Optional[np.ndarray], root: int, shape: Any, dtype: Any) -> np.ndarray:
        raise PeerLostError("peer left the membership")


# --------------------------------------------------------------------- ragged gather


def _shape_vector(x: np.ndarray) -> np.ndarray:
    return np.asarray(x.shape, dtype=np.int64) if x.ndim else np.zeros((0,), np.int64)


def gather_ragged(
    transport: Transport,
    x: np.ndarray,
    *,
    rank: Optional[int] = None,
    max_pad_ratio: float = 1.25,
) -> List[np.ndarray]:
    """Gather a possibly-ragged array from every rank, in rank order.

    The reference protocol (torchmetrics ``gather_all_tensors``): gather shape
    vectors first; equal shapes → one allgather; unequal → pad to the
    elementwise max along every dim, gather, trim each rank back. Mixed ranks
    (different ``ndim``) are a protocol error, as in the reference.

    When the transport supports exact-size broadcast and pad-to-max would ship
    more than ``max_pad_ratio``× the real payload, each rank broadcasts its
    exact buffer instead — W rounds, zero pad bytes; the transfer planner leans
    on this for heavily skewed ``cat`` states.
    """
    x = np.asarray(x)
    world = transport.world_size()
    if world == 1:
        return [x]
    shapes = transport.allgather(_shape_vector(x))
    if any(s.shape != shapes[0].shape for s in shapes):
        ranks = sorted({int(s.size) for s in shapes})
        raise ValueError(
            f"gather_ragged: mixed-rank shards (ndims {ranks}); the pad-to-max protocol "
            "requires every process to contribute the same number of dimensions"
        )
    all_shapes = [tuple(int(d) for d in s) for s in shapes]
    if all(s == all_shapes[0] for s in all_shapes):
        return transport.allgather(x)
    max_shape = tuple(max(s[d] for s in all_shapes) for d in range(len(all_shapes[0])))
    total = sum(int(np.prod(s, dtype=np.int64)) for s in all_shapes)
    padded_total = world * int(np.prod(max_shape, dtype=np.int64))
    if rank is None:
        rank = getattr(transport, "rank", None)
    # exact-size broadcast needs to know which rank WE are (the root must pass
    # its live buffer); without that, pad-to-max is the only correct protocol
    if transport.supports_broadcast and rank is not None and total > 0 and padded_total > max_pad_ratio * total:
        out = []
        for r in range(world):
            mine = r == rank
            out.append(transport.broadcast_from(x if mine else None, r, all_shapes[r], x.dtype))
        return out
    pad = [(0, m - s) for m, s in zip(max_shape, x.shape)]
    padded = np.pad(x, pad)
    gathered = transport.allgather(padded)
    return [np.asarray(gathered[i])[tuple(slice(0, d) for d in all_shapes[i])] for i in range(world)]
