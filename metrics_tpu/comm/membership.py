"""Membership layer: who is alive, and how every survivor agrees on it.

This module turns the comm plane from fail-to-local into fail-to-quorum
(Prime PCCL's degrade-don't-deadlock posture, arxiv 2505.14065). Two pieces:

- :class:`WorldView` — one per process: per-peer health fed by *attributed*
  collective failures (``PeerLostError.peers``), cumulative suspicion
  counters (the cluster plane's failure detector consumes these), and the
  per-phase board watermarks that keep one agreement round from consuming a
  previous round's deposits.
- :func:`agree_live_set` — the two-phase agreement round. Phase A ("prop"):
  every participant deposits its presence on the transport's membership board
  and collects, under a deadline, every fresh deposit it can see — including
  opportunistic deposits from ranks it believed lost (that is automatic
  rejoin). Phase B ("commit"): every participant deposits the exact member
  tuple it observed; agreement holds only when every observed member committed
  the *same* tuple. A mismatch or a silent member drops to a retry round with
  the candidate set shrunk to the ranks that both showed up and committed —
  the candidate set can only shrink within a round sequence, so the loop is
  bounded; exhaustion raises :class:`MembershipError` and the sync ladder
  falls through to ``local_state``.

Why this is safe for metric state: every state is mergeable *cumulative*
full-state (the ``add_state(dist_reduce_fx=...)`` contract) — a sync over the
agreed sub-world is exactly the correct aggregate of the surviving ranks, and
a rejoined rank's next sync contributes its whole cumulative state, so nothing
is double-counted and nothing is lost (see docs/source/comm.md).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Sequence, Tuple

from metrics_tpu.obs import instrument as _obs

__all__ = [
    "MembershipError",
    "WorldView",
    "agree_live_set",
    "view_for",
]


class MembershipError(RuntimeError):
    """The survivors could not agree on a live set (quorum lost or rounds exhausted)."""


class WorldView:
    """Per-process view of which ranks are live, with suspicion bookkeeping.

    Thread-safe: the owning rank mutates it from the sync path while the
    cluster plane's failure detector reads :meth:`suspicion` from its tick
    thread. All mutation is attributed — a peer only becomes lost via an
    attributed collective failure (:meth:`mark_lost`), an explicit
    :meth:`suspect_all` (a restarting process must re-agree before its first
    sync), or a committed agreement round (:meth:`commit`).
    """

    def __init__(self, world: int, rank: int) -> None:
        self.world = int(world)
        self.rank = int(rank)
        self._lock = threading.Lock()
        self._lost: set = set()
        self._suspicion: Dict[int, int] = {}
        self._watermarks: Dict[str, Dict[int, int]] = {}
        self.epoch = 0
        self.last_agreed: Tuple[int, ...] = tuple(range(self.world))

    # ------------------------------------------------------------------ queries

    def live(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(r for r in range(self.world) if r not in self._lost)

    def lost(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._lost))

    def has_lost(self) -> bool:
        with self._lock:
            return bool(self._lost)

    def is_live(self, peer: int) -> bool:
        with self._lock:
            return int(peer) not in self._lost

    def suspicion(self) -> Dict[int, int]:
        """Cumulative attributed-failure counts per peer (never reset — the
        cluster plane reads edges, not levels)."""
        with self._lock:
            return dict(self._suspicion)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "world": self.world,
                "rank": self.rank,
                "epoch": self.epoch,
                "live": tuple(r for r in range(self.world) if r not in self._lost),
                "lost": tuple(sorted(self._lost)),
                "suspicion": dict(self._suspicion),
            }

    # ---------------------------------------------------------------- mutation

    def mark_lost(self, peers: Sequence[int], reason: str = "") -> None:
        with self._lock:
            for p in peers:
                p = int(p)
                if p == self.rank or not 0 <= p < self.world:
                    continue
                self._lost.add(p)
                self._suspicion[p] = self._suspicion.get(p, 0) + 1

    def observe_alive(self, peers: Sequence[int]) -> None:
        with self._lock:
            for p in peers:
                self._lost.discard(int(p))

    def suspect_all(self) -> None:
        """Mark every peer lost — a restarting/rejoining process calls this so
        its first sync goes through agreement instead of stalling a full-world
        collective it cannot complete alone."""
        with self._lock:
            for p in range(self.world):
                if p != self.rank:
                    self._lost.add(p)
                    self._suspicion[p] = self._suspicion.get(p, 0) + 1

    def commit(self, agreed: Sequence[int]) -> Tuple[int, ...]:
        agreed_t = tuple(sorted(int(r) for r in agreed))
        with self._lock:
            previous = self.last_agreed
            self._lost = set(range(self.world)) - set(agreed_t)
            self._lost.discard(self.rank)
            self.epoch += 1
            self.last_agreed = agreed_t
        # flight-recorder evidence (+ bundle dump when the live set SHRANK):
        # outside the lock — the dump walks registry/tracer state
        _obs.record_comm_live_set(f"rank{self.rank}", previous, agreed_t)
        return agreed_t

    def watermarks(self, phase: str) -> Dict[int, int]:
        """The (mutable) consumed-seq watermark map for one board phase."""
        with self._lock:
            return self._watermarks.setdefault(phase, {})


_VIEW_ATTR = "_metrics_tpu_world_view"


def view_for(transport: Any) -> WorldView:
    """The :class:`WorldView` attached to a transport (created on first use).

    Views live on the transport object so one process keeps one opinion per
    world across syncs; a fresh transport (a restarted process) starts with a
    clean all-live view — call :meth:`WorldView.suspect_all` on restart so the
    first sync re-agrees instead of assuming the old world.
    """
    view = getattr(transport, _VIEW_ATTR, None)
    if view is None:
        rank = getattr(transport, "rank", None)
        view = WorldView(transport.world_size(), int(rank) if rank is not None else 0)
        try:
            setattr(transport, _VIEW_ATTR, view)
        except (AttributeError, TypeError):
            pass
    return view


def agree_live_set(
    transport: Any,
    view: WorldView,
    *,
    deadline_s: float,
    grace_s: Optional[float] = None,
    max_rounds: Optional[int] = None,
) -> Tuple[int, ...]:
    """Run two-phase live-set agreement; returns the agreed member tuple.

    Every completing participant returns the SAME tuple (the phase-B equality
    check guarantees it), the view is committed to it, and lost peers' board
    deposits can never leak across rounds (per-phase watermarks). Raises
    :class:`MembershipError` when ``max_rounds`` pass without agreement.
    """
    world = int(transport.world_size())
    me = view.rank
    if world <= 1:
        return view.commit((me,))
    if grace_s is None:
        # every participant entering within the grace window sees the others'
        # phase-A deposits, so near-simultaneous entrants converge in one round
        grace_s = max(min(0.25 * deadline_s, 0.25), 0.01)
    if max_rounds is None:
        max_rounds = world + 2
    reset = getattr(transport, "reset", None)
    if reset is not None:
        reset()  # repair barriers an aborted payload round broke

    cand = set(view.live())
    cand.add(me)
    last_observed: Tuple[int, ...] = (me,)
    for _round in range(max_rounds):
        marks_p = view.watermarks("prop")
        deposits = transport.membership_exchange(
            "prop",
            tuple(sorted(cand)),
            deadline_s=deadline_s,
            expected=sorted(cand),
            watermarks=marks_p,
            grace_s=grace_s,
        )
        for r, (seq, _payload) in deposits.items():
            marks_p[int(r)] = max(marks_p.get(int(r), -1), int(seq))
        observed = {int(r) for r in deposits} | {me}
        mask = tuple(sorted(observed))
        last_observed = mask

        marks_c = view.watermarks("commit")
        commits = transport.membership_exchange(
            "commit",
            mask,
            deadline_s=deadline_s,
            expected=mask,
            watermarks=marks_c,
            grace_s=0.0,
        )
        for r, (seq, _payload) in commits.items():
            marks_c[int(r)] = max(marks_c.get(int(r), -1), int(seq))
        committed = {int(r) for r, (_seq, payload) in commits.items() if tuple(payload) == mask}
        committed.add(me)
        if observed <= committed:
            return view.commit(mask)
        # silent or divergent members drop out; the candidate set shrinks to
        # the ranks that both proposed and committed, and the round repeats
        cand = (observed & committed) | {me}
    raise MembershipError(
        f"rank {me}: no live-set agreement after {max_rounds} rounds (last observed {last_observed})"
    )
