"""Replication plane — WAL shipping, follower replay, bounded-staleness reads,
hot failover.

The sixth plane of the serving stack, built entirely on the artifacts the
others already produce: the ckpt plane's atomic snapshots + CRC-framed
seq-numbered WAL are the replication log, the engine's recovery machinery is
the replayer, and the guard plane's health transitions are the failover
trigger. Topology is one primary (owns the write path and the durable
lineage) plus ONE read replica per ship link — every transport here is a
single-consumer stream (``recv`` consumes), so two followers must never share
a link; an engine currently wires one transport, i.e. one follower per
primary (multi-link fan-out is a transport-layer extension, not an engine
change)::

    from metrics_tpu.engine import CheckpointConfig, ReplConfig, StreamingEngine
    from metrics_tpu.repl import LoopbackLink

    link = LoopbackLink()
    primary = StreamingEngine(
        metric,
        checkpoint=CheckpointConfig(directory="/data/primary"),
        replication=ReplConfig(role="primary", transport=link),
    )
    follower = StreamingEngine(
        metric,
        replication=ReplConfig(
            role="follower", transport=link, max_staleness_s=2.0,
            promote_checkpoint=CheckpointConfig(directory="/data/follower"),
        ),
    )
    follower.compute(key)          # read replica: refused beyond max_staleness
    follower.replica_lag()         # ReplicaLag(seqs_behind, seconds_behind)
    follower.promote()             # hot failover: drain, fence, go writable

Failover wires through the guard plane's health-transition hook — when the
watchdog quarantines a wedged primary, the follower promotes itself::

    primary = StreamingEngine(..., guard=GuardConfig(
        watchdog_timeout_s=1.0, on_health_transition=failover_hook(follower)))

Fencing: promotion adopts ``deposed epoch + 1`` and fences the transport, so a
zombie primary's late shipments are rejected at the transport boundary and can
never leak into the promoted lineage. See ``docs/source/replication.md``.
"""

from metrics_tpu.repl.config import ReplConfig, ReplicaLag
from metrics_tpu.repl.errors import (
    FencedError,
    NotPrimaryError,
    ReplPeerLostError,
    ReplTransportError,
    StalenessExceeded,
)
from metrics_tpu.repl.replica import ReplicaApplier
from metrics_tpu.repl.shipper import Shipper
from metrics_tpu.repl.transport import (
    DeadPeerLink,
    DirectoryTransport,
    FlakyLink,
    HeartbeatFrame,
    LoopbackLink,
    ReplTransport,
    ShipFrame,
    SnapshotFrame,
    SocketShipReceiver,
    SocketShipSender,
    StallLink,
    WalFrame,
)

__all__ = [
    "DeadPeerLink",
    "DirectoryTransport",
    "FencedError",
    "FlakyLink",
    "HeartbeatFrame",
    "LoopbackLink",
    "NotPrimaryError",
    "ReplConfig",
    "ReplPeerLostError",
    "ReplTransport",
    "ReplTransportError",
    "ReplicaApplier",
    "ReplicaLag",
    "ShipFrame",
    "Shipper",
    "SnapshotFrame",
    "SocketShipReceiver",
    "SocketShipSender",
    "StalenessExceeded",
    "StallLink",
    "WalFrame",
    "failover_hook",
]


def failover_hook(follower_engine, *, on_state: str = "QUARANTINED"):
    """Build a ``GuardConfig(on_health_transition=...)`` observer that promotes
    ``follower_engine`` the moment the primary's health reaches ``on_state``.

    The guard fires the hook outside its locks and absorbs exceptions, and the
    two engines share no locks, so the promotion runs inline — by the time the
    quarantined primary's callers see their failures, the follower is already
    writable.
    """

    def _hook(old: str, new: str) -> None:
        if new == on_state and old != on_state:
            follower_engine.promote()

    return _hook
