"""Replication plane — WAL shipping, follower replay, bounded-staleness reads,
hot failover.

The sixth plane of the serving stack, built entirely on the artifacts the
others already produce: the ckpt plane's atomic snapshots + CRC-framed
seq-numbered WAL are the replication log, the engine's recovery machinery is
the replayer, and the guard plane's health transitions are the failover
trigger. Topology is one primary (owns the write path and the durable
lineage) plus ONE read replica per ship link — every transport here is a
single-consumer stream (``recv`` consumes), so two followers must never share
a link; a primary reaches N followers by wiring a
:class:`~metrics_tpu.repl.transport.FanoutTransport` over N single-consumer
links — the fan-out happens at the transport layer, not in the engine::

    from metrics_tpu.engine import CheckpointConfig, ReplConfig, StreamingEngine
    from metrics_tpu.repl import LoopbackLink

    link = LoopbackLink()
    primary = StreamingEngine(
        metric,
        checkpoint=CheckpointConfig(directory="/data/primary"),
        replication=ReplConfig(role="primary", transport=link),
    )
    follower = StreamingEngine(
        metric,
        replication=ReplConfig(
            role="follower", transport=link, max_staleness_s=2.0,
            promote_checkpoint=CheckpointConfig(directory="/data/follower"),
        ),
    )
    follower.compute(key)          # read replica: refused beyond max_staleness
    follower.replica_lag()         # ReplicaLag(seqs_behind, seconds_behind)
    follower.promote()             # hot failover: drain, fence, go writable

Failover wires through the guard plane's health-transition hook — when the
watchdog quarantines a wedged primary, the follower promotes itself::

    primary = StreamingEngine(..., guard=GuardConfig(
        watchdog_timeout_s=1.0, on_health_transition=failover_hook(follower)))

Fencing: promotion adopts ``deposed epoch + 1`` and fences the transport, so a
zombie primary's late shipments are rejected at the transport boundary and can
never leak into the promoted lineage. See ``docs/source/replication.md``.
"""

from metrics_tpu.repl.config import ReplConfig, ReplicaLag
from metrics_tpu.repl.errors import (
    FencedError,
    NotPrimaryError,
    NotPromotableError,
    ReplPeerLostError,
    ReplTransportError,
    StalenessExceeded,
)
from metrics_tpu.repl.replica import ReplicaApplier
from metrics_tpu.repl.shipper import Shipper
from metrics_tpu.repl.transport import (
    DeadPeerLink,
    DirectoryTransport,
    FanoutTransport,
    FlakyLink,
    HeartbeatFrame,
    LoopbackLink,
    ReplTransport,
    ShipFrame,
    SnapshotFrame,
    SocketShipReceiver,
    SocketShipSender,
    StallLink,
    WalFrame,
)

__all__ = [
    "DeadPeerLink",
    "DirectoryTransport",
    "FanoutTransport",
    "FencedError",
    "FlakyLink",
    "HeartbeatFrame",
    "LoopbackLink",
    "NotPrimaryError",
    "NotPromotableError",
    "ReplConfig",
    "ReplPeerLostError",
    "ReplTransport",
    "ReplTransportError",
    "ReplicaApplier",
    "ReplicaLag",
    "ShipFrame",
    "Shipper",
    "SnapshotFrame",
    "SocketShipReceiver",
    "SocketShipSender",
    "StalenessExceeded",
    "StallLink",
    "WalFrame",
    "failover_hook",
]


def failover_hook(
    follower_engine,
    *,
    on_state: str = "QUARANTINED",
    retries: int = 20,
    backoff_s: float = 0.05,
    backoff_cap_s: float = 1.0,
):
    """Build a ``GuardConfig(on_health_transition=...)`` observer that promotes
    ``follower_engine`` the moment the primary's health reaches ``on_state``.

    The guard fires the hook outside its locks and absorbs exceptions, and the
    two engines share no locks, so the promotion runs inline — by the time the
    quarantined primary's callers see their failures, the follower is already
    writable.

    :class:`~metrics_tpu.repl.errors.NotPromotableError` is retryable by
    contract: the follower merely hasn't received its bootstrap snapshot yet
    (the primary may have died mid-ship). The hook backs off with capped
    exponential delays and retries up to ``retries`` times — if the snapshot
    never lands, it gives up quietly and leaves the follower read-only (the
    guard absorbs hook exceptions anyway; raising would change nothing).
    """
    import time as _time

    def _hook(old: str, new: str) -> None:
        if new != on_state or old == on_state:
            return
        for attempt in range(retries + 1):
            try:
                follower_engine.promote()
                return
            except NotPromotableError:
                if attempt == retries:
                    return
                _time.sleep(min(backoff_s * (2.0 ** attempt), backoff_cap_s))

    return _hook
