"""Replication-plane failure vocabulary.

Two families, split by who should catch them:

- **User errors** (:class:`NotPrimaryError`, :class:`StalenessExceeded`) extend
  :class:`~metrics_tpu.utils.exceptions.MetricsTPUUserError` — a caller hit a
  role or staleness contract and should route the request elsewhere (writes to
  the primary, stale-intolerant reads to a fresher replica).
- **Transport errors** (:class:`ReplTransportError`, :class:`FencedError`,
  :class:`ReplPeerLostError`) are the shipper/applier's internal weather: the
  ship loop absorbs and retries them, except :class:`FencedError`, which is
  terminal — a fenced sender is a deposed primary and can never ship again.
"""

from __future__ import annotations

from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = [
    "FencedError",
    "NotPrimaryError",
    "NotPromotableError",
    "ReplPeerLostError",
    "ReplTransportError",
    "StalenessExceeded",
]


class NotPrimaryError(MetricsTPUUserError):
    """A write (``submit``/``reset``/``rotate_window``) on a follower replica.

    Followers are read-only until :meth:`~metrics_tpu.engine.StreamingEngine.promote`
    flips them; route writes to the primary."""


class StalenessExceeded(MetricsTPUUserError):
    """A follower read was refused because its :class:`~metrics_tpu.repl.ReplicaLag`
    exceeded the configured ``max_staleness`` bound (or the replica has not
    bootstrapped yet, i.e. its staleness is unbounded)."""


class NotPromotableError(MetricsTPUUserError):
    """``promote()`` refused because this follower cannot safely become primary
    *yet*: it never received its bootstrap snapshot, so flipping it writable
    would pin fresh-init state as the authoritative lineage. Retryable by
    contract — automation (the guard failover hook, the cluster orchestrator)
    backs off and retries once a snapshot lands, instead of pattern-matching a
    generic error."""


class ReplTransportError(RuntimeError):
    """A ship/receive operation failed for a reason worth retrying next tick."""


class ReplPeerLostError(ReplTransportError):
    """The peer is gone for good — retrying the same link cannot succeed."""


class FencedError(ReplTransportError):
    """A frame carried an epoch below the transport's fence: the sender was
    deposed by a promotion and its shipments are permanently rejected."""
