"""Shipper — the primary's background publisher of (snapshot, WAL tail).

Runs entirely OFF the write path: one daemon thread that reads the durable
state plane's artifacts from disk (committed snapshot generations, journal
segments via :meth:`~metrics_tpu.ckpt.store.RequestJournal.read_from`) and
publishes them over the configured :class:`~metrics_tpu.repl.transport.ReplTransport`.
The dispatcher never waits on it and it takes no engine lock — the <5%%
primary-overhead gate (``benchmarks/engine_throughput.py --replica``) is the
measured consequence.

Each tick:

1. consume a follower snapshot request / detect a new committed generation →
   publish the newest VALID snapshot (corrupt generations skipped, same
   newest-first policy as recovery) as one :class:`SnapshotFrame`; with no
   snapshot on disk and a journal that starts at seq 0, an *empty bootstrap*
   frame (``data=None``) lets the follower start from fresh init state;
2. tail-follow the WAL from the last shipped seq and publish each record as a
   :class:`WalFrame`. A seq discontinuity (rotation GC'd segments past a laggy
   shipper) flips back to step 1 — the follower re-bootstraps instead of
   silently skipping records;
3. heartbeat (primary position + wall clock) when due, so a caught-up follower
   can bound ``seconds_behind`` on an idle stream.

Transient transport failures are absorbed and retried next tick (``last_error``
remembers, telemetry counts). :class:`~metrics_tpu.repl.errors.FencedError` is
terminal: the link was fenced by a promotion, this process is a deposed
primary, and the ship loop parks permanently (``fenced`` stays True).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from metrics_tpu.ckpt import format as ckpt_format
from metrics_tpu.obs import instrument as _obs
from metrics_tpu.obs.fleet import node_snapshot
from metrics_tpu.obs.registry import OBS as _OBS
from metrics_tpu.repl.config import ReplConfig
from metrics_tpu.repl.errors import FencedError
from metrics_tpu.repl.transport import HeartbeatFrame, SnapshotFrame, WalFrame

__all__ = ["Shipper"]

_WAL_BATCH = 256  # WalFrames per send — bounds per-send pickling/copy cost


class Shipper:
    """One primary's publish loop over a snapshot store + request journal."""

    def __init__(
        self,
        cfg: ReplConfig,
        *,
        store: Any,
        journal: Any,
        telemetry: Any,
        engine_label: str = "0",
        epoch: Optional[int] = None,
    ) -> None:
        self.cfg = cfg
        self.transport = cfg.transport
        # the engine passes the lineage-recovered token (snapshot meta) when it
        # exceeds the config's — a restarted promoted primary keeps its epoch
        self.epoch = int(cfg.epoch if epoch is None else epoch)
        self._store = store
        self._journal = journal
        self._telemetry = telemetry
        self._engine_label = engine_label

        self.last_shipped_seq = -1
        self.shipped_generation: Optional[int] = None
        # newest generation last ATTEMPTED (shipped or skipped-as-corrupt): a
        # corrupt newest must not trigger a full re-scan + re-ship every tick
        self._seen_generation: Optional[int] = None
        self.fenced = False
        self.journal_lost = False
        self.ship_failures = 0  # transient tick failures (health() surfaces this)
        self.last_error: Optional[BaseException] = None
        self._need_snapshot = True  # first attach always bootstraps the follower
        self._final = False  # close()'s last publish: lets the tail loop run past _stop
        # (newest generation, journal start) the bootstrap parked on: the best
        # valid snapshot + retained WAL couldn't form a chain — don't re-read/
        # re-verify/re-ship until either side of the pair changes
        self._hole_park: Optional[Any] = None
        self._cursor: Optional[Any] = None  # incremental journal tail position
        self._last_heartbeat = 0.0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="metrics-tpu-repl-ship", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------ lifecycle

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=timeout)
        if self.fenced or self.journal_lost or self._thread.is_alive():
            # fenced/journal-lost loops are parked deliberately; a thread that
            # outlived its join may still be mid-tick — no concurrent publish
            return
        try:
            # one FINAL publish: the engine commits its close-time checkpoint
            # before closing the shipper, so everything acked since the last
            # periodic tick — up to a full ship interval's worth of records,
            # plus the final snapshot's generation — is still unpublished
            # here. Exiting without it hands a promoted follower a state
            # missing acknowledged writes after a perfectly graceful shutdown.
            self._final = True
            self.tick()
        except Exception as exc:  # noqa: BLE001 — closing: record, never raise
            self.last_error = exc

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.ship_interval_s):
            try:
                self.tick()
                # full clean pass: a previously-recorded transient is healed
                # (health() stops reporting the link DEGRADED); a persistent
                # failure re-raises every tick and stays visible
                self.last_error = None
            except FencedError as exc:
                # deposed: a newer primary fenced the link. Shipping can never
                # succeed again — park instead of spinning on rejections.
                self.last_error = exc
                self.fenced = True
                self._telemetry.count("fenced_rejections")
                return
            except Exception as exc:  # noqa: BLE001 — transient: retry next tick
                self.last_error = exc
                self.ship_failures += 1
                self._telemetry.count("ship_failures")

    # ------------------------------------------------------------------ ship loop

    def tick(self) -> None:
        """One publish pass (public so tests can drive the loop synchronously)."""
        if self.journal_lost:
            # the engine disabled its WAL after an IO failure: new writes no
            # longer reach the journal, so anything published from here on —
            # above all heartbeats stamping the frozen last_seq — would tell
            # the follower it is CURRENT while the primary diverges unbounded.
            # Going silent is the conservative contract: the follower's
            # seconds_behind grows and bounded reads start refusing.
            return
        t_wall = time.time()
        if self.transport.take_snapshot_request():
            self._need_snapshot = True
        newest = self._newest_generation()
        # routine new-generation ships exist to heal links with NO backchannel
        # (a gapped socket follower can't ask for a bootstrap — the periodic
        # snapshot, with the tail rewound under it, is its only way back).
        # On a backchannel link the follower REQUESTS when it needs one, so a
        # routine ship is a full-state transfer the caught-up follower just
        # drops — pure churn, skipped.
        if self._need_snapshot or (
            newest is not None
            and newest != self._seen_generation
            and not self.transport.has_backchannel
        ):
            self._ship_snapshot(newest, t_wall)
        self._ship_tail(t_wall)
        # pacing on the monotonic clock: a backwards NTP step on wall time must
        # not silence heartbeats (the frame itself still carries t_wall — it
        # only ORDERS advancements on the follower, never ages them)
        now_mono = time.monotonic()
        if now_mono - self._last_heartbeat >= self.cfg.heartbeat_interval_s:
            # piggyback the primary's telemetry snapshot on the heartbeat it
            # already sends — the follower's aggregator merges it into the
            # fleet view with zero new transport surface
            fleet = None
            if _OBS.enabled:
                try:
                    fleet = node_snapshot(f"primary:{self._engine_label}")
                except Exception:  # noqa: BLE001 — telemetry must not break shipping
                    fleet = None
            self.transport.send(
                [HeartbeatFrame(self.epoch, int(self._journal.last_seq), t_wall, fleet)]
            )
            self._last_heartbeat = now_mono

    def _newest_generation(self) -> Optional[int]:
        gens = self._store.generations()
        return gens[-1] if gens else None

    def _ship_snapshot(self, newest: Optional[int], t_wall: float) -> None:
        """Publish the newest snapshot that validates clean (newest-first scan,
        corrupt generations skipped — the recovery policy, applied to shipping)."""
        # a (re)bootstrapping follower — fresh attach, backchannel request, or a
        # tail discontinuity — resumes WAL replay at the SNAPSHOT's position,
        # so the tail must rewind there with it: keeping the old tail position
        # would strand records (snapshot seq, last_shipped] unshipped, and under
        # live traffic the replacement follower re-gaps on every bootstrap,
        # forever (a still-current follower just drops the rewound duplicates).
        # Routine new-generation ships (bootstrap False) keep the tail put.
        bootstrap = self._need_snapshot
        segs = self._journal._segments()
        start = segs[0][0] if segs else None
        if self._hole_park is not None and self._hole_park == (newest, start):
            return  # still unserviceable (see below): wait for a new generation
        for gen in reversed(self._store.generations()):
            try:
                data = self._store.read(gen)
                # full CRC validation before shipping — but no leaf decode: the
                # frame carries the raw bytes, and the seq rides in the manifest
                # meta, so rebuilding the whole tree here would be pure waste
                manifest = ckpt_format.verify(data)
                seq = int(manifest.get("meta", {}).get("seq", -1))
            except Exception:  # noqa: BLE001 — torn/corrupt: fall back one generation
                continue
            if seq < int(self._journal.last_seq) and (start is None or start > seq + 1):
                # history hole: the newest generation is corrupt AND rotation
                # already GC'd the records between this (older) snapshot and
                # the retained journal — no chain can be anchored here. A
                # follower restoring it would gap on the very next record, we
                # would re-ship the full state every tick, and it would never
                # pass the hole (livelock). Park until a new generation
                # commits (or history changes); staleness on the follower
                # grows and bounded reads refuse — the conservative contract.
                self._hole_park = (newest, start)
                self._seen_generation = newest
                self._telemetry.count("ship_history_holes")
                return
            self.transport.send(
                [SnapshotFrame(self.epoch, gen, seq, data, t_wall, bootstrap=bootstrap)]
            )
            # seen only once the send LANDS (or every generation proved
            # corrupt, below): marking before the send would let a transient
            # transport failure eat a routine new-generation ship for good —
            # on a backchannel-less link that ship is the only thing that can
            # un-park a gapped follower before the NEXT checkpoint interval
            self._seen_generation = newest
            self._hole_park = None
            self.shipped_generation = gen
            if bootstrap or seq < self.last_shipped_seq:
                # anchor the tail AT the snapshot — rewind, never advance. A
                # bootstrapping follower resumes WAL replay at the snapshot's
                # position, so records above it must re-ship (a still-current
                # follower just drops the duplicates). The rewind also holds
                # for ROUTINE ships on backchannel-less links: a follower
                # gapped by an in-flight TCP loss restores this snapshot, and
                # without the rewind the records between the snapshot and the
                # live tip would never arrive — it would re-gap on the next
                # frame and loop restore→gap forever. The rewound span is only
                # what landed since the generation committed (one ship
                # interval), so the duplicate churn is a tick's worth of
                # records per checkpoint. ADVANCING the tail is still illegal:
                # a bootstrapped, gap-free follower drops routine snapshots
                # (WAL continuity is its contract), so skipping to the
                # snapshot's seq would strand (last_shipped, seq] unshipped
                # and park it forever — rotation's discontinuity path below is
                # the only legal skip.
                self.last_shipped_seq = seq
                self._cursor = None
            self._need_snapshot = False
            self._telemetry.count("shipped_snapshots")
            return
        # every generation proved corrupt: remember we looked so a rotting
        # newest doesn't trigger a full re-scan every tick (a NEW generation
        # still re-triggers, and _need_snapshot keeps its own retry loop)
        self._seen_generation = newest
        # no valid snapshot on disk: an empty bootstrap is only complete if the
        # journal's history starts at seq 0 (nothing was ever rotated away)
        if not segs or segs[0][0] == 0:
            self.transport.send(
                [SnapshotFrame(self.epoch, -1, -1, None, t_wall, bootstrap=bootstrap)]
            )
            self.shipped_generation = None
            if bootstrap or self.last_shipped_seq > -1:
                # same anchor rule as above: a follower restoring this empty
                # bootstrap resumes at -1, so the whole journal must re-ship
                self.last_shipped_seq = -1
                self._cursor = None
            self._need_snapshot = False
            self._telemetry.count("shipped_snapshots")
        # else: keep _need_snapshot set; the next committed generation ships

    def _ship_tail(self, t_wall: float) -> None:
        if self._need_snapshot:
            return  # nothing to anchor the tail to yet
        # incremental cursor: each tick reads only NEW journal bytes. A send
        # failure leaves last_shipped_seq at the last DELIVERED record — the
        # cursor is then ahead of it, so rebuild it at the delivered position
        # and retransmit (the follower's seq chain drops any duplicates).
        if self._cursor is None or self._cursor.seq != self.last_shipped_seq:
            self._cursor = self._journal.tail_cursor(self.last_shipped_seq)
        shipped = 0
        while self._final or not self._stop.is_set():
            # stop-aware: a deep catch-up (a follower re-attaching behind a
            # 100k-record backlog) must yield to close() between batches, not
            # outlive its join timeout publishing into a torn-down transport
            records = self._cursor.read(max_records=_WAL_BATCH)
            if not records:
                if self._journal.last_seq > self.last_shipped_seq:
                    # the cursor is dry but the journal is ahead: if rotation
                    # GC'd the unshipped span (snapshot-covered) there is no
                    # WAL frame left to trip the gap check below — on a
                    # backchannel link the follower never gaps, never asks,
                    # and the span is silently lost. Re-anchor via snapshot.
                    # (A span still buffered in an unflushed segment keeps its
                    # start at last_shipped+1 and does NOT trigger this.)
                    segs = self._journal._segments()
                    start = segs[0][0] if segs else self._journal.last_seq + 1
                    if start > self.last_shipped_seq + 1:
                        self._need_snapshot = True
                        self._cursor = None
                break
            if records[0][0] != self.last_shipped_seq + 1:
                # rotation GC'd past us while we lagged: records between
                # last_shipped and here are snapshot-covered — re-bootstrap
                self._need_snapshot = True
                self._cursor = None
                break
            self.transport.send(
                [WalFrame(self.epoch, seq, payload, t_wall) for seq, payload in records]
            )
            # delivered: only now does the cursor's progress become durable
            self.last_shipped_seq = records[-1][0]
            shipped += len(records)
        if shipped:
            self._telemetry.count("shipped_records", shipped)
            _obs.record_repl_shipped(self._engine_label, shipped)

    # ------------------------------------------------------------------ raising

    def mark_journal_lost(self) -> None:
        """Engine callback: the WAL was disabled after an IO failure. Park the
        publish loop (see :meth:`tick`) so the follower's staleness grows
        instead of being refreshed against a frozen journal position."""
        if not self.journal_lost:
            self.journal_lost = True
            self._telemetry.count("ship_journal_lost")
