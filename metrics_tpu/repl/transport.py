"""Replication links: how (snapshot, WAL tail) frames travel primary → follower.

A :class:`ReplTransport` is the frame-level boundary of the replication plane,
mirroring :mod:`metrics_tpu.comm.transport`'s shape: concrete links for real
deployments, an in-process loopback for tests, and fault doubles that wrap any
inner link. The contract is a one-way ordered stream of :class:`ShipFrame`\\ s
(:class:`SnapshotFrame` / :class:`WalFrame` / :class:`HeartbeatFrame`) plus a
tiny backchannel (``request_snapshot``) a lagging follower uses to ask for a
fresh bootstrap instead of waiting out the primary's checkpoint interval.

**Fencing is enforced at this boundary.** Every frame carries the sender's
epoch; :meth:`ReplTransport.fence` raises the link's minimum acceptable epoch
(monotone). After a promotion fences the link, a deposed primary's late
shipments are rejected — on the send side with :class:`FencedError` where the
sender can see the fence (loopback shares the object, the directory link reads
the fence file), and unconditionally on the receive side, where the check is
authoritative (``fenced_rejected`` counts the drops). A zombie primary can
therefore never leak a write into a promoted follower's lineage.

Concrete links:

- :class:`LoopbackLink` — in-process deque + condvar; the unit-test and
  single-process (thread-per-replica) link.
- :class:`DirectoryTransport` — a spool directory of CRC-checked, atomically
  renamed frame files; works across processes on one host (the kill-soak's
  link) and over any shared filesystem.
- :class:`SocketShipSender` / :class:`SocketShipReceiver` — length-prefixed
  frames over TCP for real two-host pairs (fencing is receiver-side).
- :class:`FlakyLink` / :class:`StallLink` / :class:`DeadPeerLink` — fault
  injectors mirroring the comm plane's Flaky/Stall/DeadPeer taxonomy (Prime
  PCCL's failure model: peers join, lag, and die without stopping the service).
"""

from __future__ import annotations

import os
import pickle
import select
import socket
import struct
import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

from metrics_tpu.ckpt.store import atomic_write
from metrics_tpu.repl.errors import FencedError, ReplPeerLostError, ReplTransportError

__all__ = [
    "DeadPeerLink",
    "DirectoryTransport",
    "FanoutTransport",
    "FlakyLink",
    "HeartbeatFrame",
    "LoopbackLink",
    "ReplTransport",
    "ShipFrame",
    "SnapshotFrame",
    "SocketShipReceiver",
    "SocketShipSender",
    "StallLink",
    "WalFrame",
]


# ------------------------------------------------------------------------- frames


class ShipFrame:
    """Base of the three wire frames; ``epoch`` is the sender's fencing token."""

    __slots__ = ("epoch", "t_wall")

    def __init__(self, epoch: int, t_wall: float) -> None:
        self.epoch = int(epoch)
        self.t_wall = float(t_wall)


class SnapshotFrame(ShipFrame):
    """One full engine snapshot: ``data`` is the committed container bytes
    (``None`` = empty bootstrap — the follower starts from fresh init state),
    ``seq`` the WAL position the snapshot covers. ``bootstrap`` marks a
    (re)bootstrap ship — fresh attach, backchannel request, or a WAL-tail
    discontinuity where rotation GC'd records before they were ever shipped:
    the records up to ``seq`` will NEVER arrive as WalFrames, so a follower
    behind ``seq`` must restore rather than wait on the chain. Routine
    new-generation ships (``bootstrap=False``) are droppable by a follower
    whose seq chain is intact."""

    __slots__ = ("generation", "seq", "data", "bootstrap")

    def __init__(
        self,
        epoch: int,
        generation: int,
        seq: int,
        data: Optional[bytes],
        t_wall: float,
        bootstrap: bool = False,
    ) -> None:
        super().__init__(epoch, t_wall)
        self.generation = int(generation)
        self.seq = int(seq)
        self.data = data
        self.bootstrap = bool(bootstrap)


class WalFrame(ShipFrame):
    """One journaled record, exactly as the primary's WAL framed it."""

    __slots__ = ("seq", "payload")

    def __init__(self, epoch: int, seq: int, payload: bytes, t_wall: float) -> None:
        super().__init__(epoch, t_wall)
        self.seq = int(seq)
        self.payload = payload


class HeartbeatFrame(ShipFrame):
    """Primary liveness + position: lets a caught-up follower keep its
    ``seconds_behind`` near zero even when no traffic flows.

    ``fleet`` piggybacks the primary's telemetry snapshot
    (:func:`metrics_tpu.obs.fleet.node_snapshot`) on the channel the pair
    already owns — None unless obs is enabled on the sender. Frames pickled by
    an older build restore without the slot; read it with
    ``getattr(frame, "fleet", None)``.
    """

    __slots__ = ("last_seq", "fleet")

    def __init__(
        self,
        epoch: int,
        last_seq: int,
        t_wall: float,
        fleet: Optional[Dict[str, Any]] = None,
    ) -> None:
        super().__init__(epoch, t_wall)
        self.last_seq = int(last_seq)
        self.fleet = fleet


# ----------------------------------------------------------------------- contract


class ReplTransport:
    """Frame-level replication boundary: ordered one-way stream + fence."""

    name = "repl"
    # capability flag: True when request_snapshot/take_snapshot_request are a
    # real follower→primary channel. The shipper keys its routine-ship policy
    # on this — backchannel links suppress routine new-generation snapshots
    # (the follower asks when it needs one); backchannel-less links rely on
    # them, with the WAL tail rewound under each, as the only gap-heal path.
    has_backchannel = False

    def __init__(self) -> None:
        self._fence_lock = threading.Lock()
        self._fenced_epoch = 0
        self.fenced_rejected = 0  # frames dropped at the receive-side fence check

    # -------------------------------------------------------------- ship side

    def send(self, frames: Sequence[ShipFrame]) -> None:
        """Publish frames in order. Raises :class:`FencedError` when the sender's
        epoch is below the fence (where the fence is visible to the sender)."""
        raise NotImplementedError

    # ----------------------------------------------------------- receive side

    def recv(self, timeout_s: float = 0.0) -> List[ShipFrame]:
        """Every frame available now (waiting up to ``timeout_s`` for the first),
        in ship order, fenced frames already dropped."""
        raise NotImplementedError

    # ---------------------------------------------------------------- fencing

    def fence(self, epoch: int) -> None:
        """Reject every frame with ``frame.epoch < epoch`` from now on (monotone)."""
        with self._fence_lock:
            self._fenced_epoch = max(self._fenced_epoch, int(epoch))

    @property
    def fenced_epoch(self) -> int:
        return self._fenced_epoch

    def _check_send_epoch(self, frames: Sequence[ShipFrame]) -> None:
        fence = self._fenced_epoch
        for frame in frames:
            if frame.epoch < fence:
                raise FencedError(
                    f"shipment at epoch {frame.epoch} rejected: link fenced at epoch {fence} "
                    "(a newer primary was promoted)"
                )

    def _filter_fenced(self, frames: List[ShipFrame]) -> List[ShipFrame]:
        fence = self._fenced_epoch
        kept = [f for f in frames if f.epoch >= fence]
        self.fenced_rejected += len(frames) - len(kept)
        return kept

    # ------------------------------------------------------------ backchannel

    def request_snapshot(self) -> None:
        """Follower → primary: 'I need a fresh bootstrap'. Optional; links
        without a backchannel (``has_backchannel`` False) no-op — there the
        shipper ships each new generation routinely with the WAL tail rewound
        under it, so rejoin latency is bounded by the ckpt interval."""

    def take_snapshot_request(self) -> bool:
        """Primary-side poll: consume one pending snapshot request."""
        return False

    def close(self) -> None:
        pass


# ----------------------------------------------------------------- loopback link


class LoopbackLink(ReplTransport):
    """In-process link: one deque, condvar-signalled — primary and follower in
    the same process (tests, thread-per-replica deployments). The fence is one
    shared token, so it is enforced on BOTH sides."""

    name = "loopback"
    has_backchannel = True

    def __init__(self, maxlen: Optional[int] = 8192) -> None:
        super().__init__()
        self._cond = threading.Condition()
        # bounded by default for the same reason DirectoryTransport caps its
        # spool: a wedged in-process follower must not grow the SHARED
        # process's memory until the primary OOMs with it. deque(maxlen)
        # drops the OLDEST frames; the follower sees the seq gap and
        # re-bootstraps over the backchannel — the protocol's normal heal
        # path. maxlen=None opts back into an unbounded link.
        self._frames: deque = deque(maxlen=maxlen)
        self._snap_request = threading.Event()

    def send(self, frames: Sequence[ShipFrame]) -> None:
        self._check_send_epoch(frames)
        with self._cond:
            self._frames.extend(frames)
            self._cond.notify_all()

    def recv(self, timeout_s: float = 0.0) -> List[ShipFrame]:
        with self._cond:
            if not self._frames and timeout_s > 0:
                self._cond.wait(timeout_s)
            out = list(self._frames)
            self._frames.clear()
        # frames enqueued before the fence rose are still subject to it
        return self._filter_fenced(out)

    @property
    def pending(self) -> int:
        with self._cond:
            return len(self._frames)

    def request_snapshot(self) -> None:
        self._snap_request.set()

    def take_snapshot_request(self) -> bool:
        was_set = self._snap_request.is_set()
        self._snap_request.clear()
        return was_set


# ---------------------------------------------------------------- directory link

_DIR_PREFIX = "ship-"
_DIR_SUFFIX = ".frm"
_FENCE_NAME = "FENCE"
_SNAPREQ_NAME = "SNAPREQ"
_CRC = struct.Struct("<I")


class DirectoryTransport(ReplTransport):
    """Frame spool in a shared directory — the cross-process link on one host
    (or any shared filesystem). Each ``send`` commits one serial-numbered,
    CRC-checked file via the ckpt plane's atomic temp+rename, so the receiver
    never observes a torn batch; ``recv`` consumes files in serial order and
    deletes them (the spool stays bounded by the follower's lag).

    The fence is a ``FENCE`` file holding the epoch: ``fence()`` commits it,
    senders re-read it before every publish (send-side rejection), and the
    receive-side filter re-checks each frame — authoritative even when a racing
    sender's file landed between the fence commit and its next read.
    """

    name = "directory"
    has_backchannel = True

    def __init__(self, root: str, *, durable: bool = False, max_spool_files: int = 8192) -> None:
        super().__init__()
        self.root = os.path.abspath(root)
        self.durable = durable
        # "the spool stays bounded by the follower's lag" only holds while a
        # follower is consuming — a permanently dead one would otherwise grow
        # the spool without bound until the DISK fills (and take the ckpt
        # plane's own writes down with it on a shared filesystem). Beyond the
        # cap the OLDEST batches drop: a returning follower sees the seq gap
        # and re-bootstraps — exactly the protocol's normal heal path, so
        # bounding the spool costs one snapshot restore, not correctness.
        self.max_spool_files = int(max_spool_files)
        self.spool_dropped = 0
        os.makedirs(self.root, exist_ok=True)
        serials = self._serials()
        self._next_serial = (serials[-1] + 1) if serials else 0
        self._trim_floor = serials[0] if serials else 0  # lowest serial possibly on disk

    def _serials(self) -> List[int]:
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            if name.startswith(_DIR_PREFIX) and name.endswith(_DIR_SUFFIX):
                try:
                    out.append(int(name[len(_DIR_PREFIX) : -len(_DIR_SUFFIX)]))
                except ValueError:
                    continue
        return sorted(out)

    def _path(self, serial: int) -> str:
        return os.path.join(self.root, f"{_DIR_PREFIX}{serial:016d}{_DIR_SUFFIX}")

    def _disk_fence(self) -> int:
        try:
            with open(os.path.join(self.root, _FENCE_NAME), "rb") as f:
                return int(f.read().decode() or 0)
        except (OSError, ValueError):
            return 0

    def fence(self, epoch: int) -> None:
        super().fence(epoch)
        current = max(self._disk_fence(), self._fenced_epoch)
        atomic_write(
            os.path.join(self.root, _FENCE_NAME), str(current).encode(), durable=self.durable
        )

    def send(self, frames: Sequence[ShipFrame]) -> None:
        if not frames:
            return
        # the on-disk fence is the shared token: a promotion in another process
        # must depose this sender too
        with self._fence_lock:
            self._fenced_epoch = max(self._fenced_epoch, self._disk_fence())
        self._check_send_epoch(frames)
        payload = pickle.dumps(list(frames), protocol=pickle.HIGHEST_PROTOCOL)
        blob = _CRC.pack(zlib.crc32(payload) & 0xFFFFFFFF) + payload
        atomic_write(self._path(self._next_serial), blob, durable=self.durable)
        self._next_serial += 1
        if self.max_spool_files > 0:
            # serials are dense from this sender, so the cap walks a floor —
            # no listdir on the publish hot path (a remove that fails was
            # already consumed by a live follower, which is the common case)
            floor = self._next_serial - self.max_spool_files
            while self._trim_floor < floor:
                try:
                    os.remove(self._path(self._trim_floor))
                    self.spool_dropped += 1
                except OSError:
                    pass
                self._trim_floor += 1

    def recv(self, timeout_s: float = 0.0) -> List[ShipFrame]:
        deadline = time.monotonic() + timeout_s
        while True:
            with self._fence_lock:
                self._fenced_epoch = max(self._fenced_epoch, self._disk_fence())
            out: List[ShipFrame] = []
            for serial in self._serials():
                path = self._path(serial)
                try:
                    with open(path, "rb") as f:
                        blob = f.read()
                    os.remove(path)
                except OSError:
                    continue
                if len(blob) < _CRC.size:
                    continue
                (crc,) = _CRC.unpack_from(blob)
                payload = blob[_CRC.size :]
                if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                    continue  # torn/corrupt spool file: skip, the WAL seq chain catches gaps
                try:
                    out.extend(pickle.loads(payload))
                except Exception:  # noqa: BLE001 — a corrupt batch is a gap, not a crash
                    continue
            if out or time.monotonic() >= deadline:
                return self._filter_fenced(out)
            time.sleep(0.005)

    def request_snapshot(self) -> None:
        atomic_write(os.path.join(self.root, _SNAPREQ_NAME), b"1", durable=self.durable)

    def take_snapshot_request(self) -> bool:
        try:
            os.remove(os.path.join(self.root, _SNAPREQ_NAME))
            return True
        except OSError:
            return False


# ------------------------------------------------------------------- socket link

_SOCK_LEN = struct.Struct("<Q")


class SocketShipReceiver(ReplTransport):
    """Listening end of a TCP ship link (the follower). Accepts one sender at a
    time (reconnects allowed — a restarted primary re-attaches), buffers frames
    on a background thread; fencing is enforced here, the authoritative side."""

    name = "socket"

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        super().__init__()
        self._cond = threading.Condition()
        self._frames: deque = deque()
        self._closed = False
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(1)
        self._server.settimeout(0.2)
        self.host, self.port = self._server.getsockname()
        self._thread = threading.Thread(
            target=self._serve, name="metrics-tpu-repl-recv", daemon=True
        )
        self._thread.start()

    def _serve(self) -> None:
        # one active sender at a time, NEWEST connection wins: a live zombie
        # primary holding the old connection must not starve a replacement
        # primary out of the accept queue forever — the takeover closes the
        # zombie's socket, and once any replacement frame reaches the applier
        # the higher epoch makes it drop the zombie's stragglers too
        conn: Optional[socket.socket] = None
        buf = b""
        try:
            while not self._closed:
                watch = [self._server] if conn is None else [self._server, conn]
                try:
                    readable, _, _ = select.select(watch, [], [], 0.2)
                except (OSError, ValueError):
                    if conn is not None:
                        conn.close()
                        conn, buf = None, b""
                        continue
                    return
                if self._server in readable:
                    try:
                        new_conn, _ = self._server.accept()
                    except OSError:
                        return
                    if conn is not None:
                        try:
                            conn.close()
                        except OSError:
                            pass
                    conn, buf = new_conn, b""
                    continue  # re-select: the fresh sender may already have data
                if conn is None or conn not in readable:
                    continue
                try:
                    chunk = conn.recv(1 << 16)
                except OSError:
                    chunk = b""
                if not chunk:
                    conn.close()
                    conn, buf = None, b""
                    continue
                buf += chunk
                while len(buf) >= _SOCK_LEN.size:
                    (n,) = _SOCK_LEN.unpack_from(buf)
                    if len(buf) < _SOCK_LEN.size + n:
                        break
                    payload = buf[_SOCK_LEN.size : _SOCK_LEN.size + n]
                    buf = buf[_SOCK_LEN.size + n :]
                    try:
                        frames = pickle.loads(payload)
                    except Exception:  # noqa: BLE001 — corrupt batch = gap, WAL chain catches it
                        continue
                    with self._cond:
                        self._frames.extend(frames)
                        self._cond.notify_all()
        finally:
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass

    def send(self, frames: Sequence[ShipFrame]) -> None:
        raise ReplTransportError("SocketShipReceiver is the receiving end; ship via SocketShipSender")

    def recv(self, timeout_s: float = 0.0) -> List[ShipFrame]:
        with self._cond:
            if not self._frames and timeout_s > 0:
                self._cond.wait(timeout_s)
            out = list(self._frames)
            self._frames.clear()
        return self._filter_fenced(out)

    def close(self) -> None:
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass


class SocketShipSender(ReplTransport):
    """Connecting end of a TCP ship link (the primary). Lazily connects;
    transient socket failures surface as :class:`ReplTransportError` (the
    shipper retries next tick, reconnecting). The fence here is local-process
    only — the receiver's check is what actually stops a remote zombie."""

    name = "socket"

    def __init__(self, host: str, port: int, *, connect_timeout_s: float = 5.0) -> None:
        super().__init__()
        self._addr = (host, int(port))
        self._connect_timeout_s = connect_timeout_s
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connected(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(self._addr, timeout=self._connect_timeout_s)
            s.settimeout(self._connect_timeout_s)
            self._sock = s
        return self._sock

    def send(self, frames: Sequence[ShipFrame]) -> None:
        if not frames:
            return
        self._check_send_epoch(frames)
        payload = pickle.dumps(list(frames), protocol=pickle.HIGHEST_PROTOCOL)
        with self._lock:
            try:
                sock = self._connected()
                sock.sendall(_SOCK_LEN.pack(len(payload)) + payload)
            except OSError as exc:
                self._drop_connection()
                raise ReplTransportError(f"ship link send failed: {exc!r}") from exc

    def recv(self, timeout_s: float = 0.0) -> List[ShipFrame]:
        raise ReplTransportError("SocketShipSender is the shipping end; receive via SocketShipReceiver")

    def _drop_connection(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._drop_connection()


# ----------------------------------------------------------------- fault doubles


class FlakyLink(ReplTransport):
    """Fail the first ``fail`` sends, then delegate — the transient-fault
    injector for shipper-retry tests (mirrors ``comm.FlakyTransport``)."""

    name = "flaky"

    def __init__(
        self,
        inner: ReplTransport,
        fail: int = 1,
        exc: Callable[[], Exception] = ReplTransportError,
    ) -> None:
        super().__init__()
        self._inner = inner
        self._remaining = int(fail)
        self._exc = exc
        self.failures_injected = 0

    @property
    def has_backchannel(self) -> bool:  # type: ignore[override]
        return self._inner.has_backchannel

    def send(self, frames: Sequence[ShipFrame]) -> None:
        if self._remaining > 0:
            self._remaining -= 1
            self.failures_injected += 1
            raise self._exc()
        self._inner.send(frames)

    def recv(self, timeout_s: float = 0.0) -> List[ShipFrame]:
        return self._inner.recv(timeout_s)

    def fence(self, epoch: int) -> None:
        self._inner.fence(epoch)

    @property
    def fenced_epoch(self) -> int:  # type: ignore[override]
        return self._inner.fenced_epoch

    def request_snapshot(self) -> None:
        self._inner.request_snapshot()

    def take_snapshot_request(self) -> bool:
        return self._inner.take_snapshot_request()

    def close(self) -> None:
        self._inner.close()


class StallLink(FlakyLink):
    """Sleep ``stall_s`` before the first ``stalls`` sends complete — what a
    wedged/slow link looks like to the ship loop (lag grows, nothing is lost)."""

    name = "stall"

    def __init__(self, inner: ReplTransport, stall_s: float, stalls: int = 1) -> None:
        super().__init__(inner, fail=0)
        self._stall_s = float(stall_s)
        self._stalls = int(stalls)

    def send(self, frames: Sequence[ShipFrame]) -> None:
        if self._stalls > 0:
            self._stalls -= 1
            time.sleep(self._stall_s)
        self._inner.send(frames)


class DeadPeerLink(FlakyLink):
    """Every send fails with :class:`ReplPeerLostError` — the follower is gone;
    the primary keeps serving (shipping degrades, availability does not)."""

    name = "dead_peer"

    def __init__(self, inner: Optional[ReplTransport] = None) -> None:
        super().__init__(inner if inner is not None else LoopbackLink(), fail=0)

    def send(self, frames: Sequence[ShipFrame]) -> None:
        raise ReplPeerLostError("follower left the membership")


# -------------------------------------------------------------------- fan-out


class FanoutTransport(ReplTransport):
    """Ship-side fan-out: one primary publishing ONE lineage to N followers,
    each over its own single-consumer link (the engine still wires one
    transport — this is the "transport-layer extension" the one-follower
    contract pointed at, used by the cluster plane's multi-follower topology).

    Semantics:

    - ``send`` publishes to every link. A :class:`FencedError` from ANY link
      means a newer primary exists somewhere — the send raises it (the shipper
      parks as deposed). Non-fence failures on a SUBSET of links are absorbed
      (``partial_failures`` counts them): a dead follower must not stop
      shipping to the live ones — it re-bootstraps off its own link's snapshot
      path when it returns. Only when EVERY link fails does the send raise.
    - ``fence`` propagates to every link (and the local floor).
    - the backchannel aggregates: one follower's snapshot request triggers one
      bootstrap ship, which ``send`` fans to everyone (a redundant bootstrap
      at/behind a healthy follower's position is dropped by its applier).
    - ``recv`` is not a thing: the primary's applier never reads a fan-out.
    """

    name = "fanout"

    def __init__(self, links: Sequence[ReplTransport]) -> None:
        super().__init__()
        if not links:
            raise ReplTransportError("FanoutTransport needs at least one link")
        self._links = list(links)
        # the shipper keys its routine-ship policy on this: with any
        # backchannel-less link in the set, routine new-generation ships are
        # the only gap-heal path that reaches it
        self.has_backchannel = all(link.has_backchannel for link in self._links)
        self.partial_failures = 0

    @property
    def links(self) -> List[ReplTransport]:
        return list(self._links)

    def send(self, frames: Sequence[ShipFrame]) -> None:
        self._check_send_epoch(frames)
        fenced: Optional[FencedError] = None
        errors: List[BaseException] = []
        for link in self._links:
            try:
                link.send(frames)
            except FencedError as exc:
                fenced = exc
            except Exception as exc:  # noqa: BLE001 — per-link isolation
                errors.append(exc)
        if fenced is not None:
            raise fenced
        if errors:
            if len(errors) == len(self._links):
                raise errors[0] if isinstance(errors[0], ReplTransportError) else \
                    ReplTransportError(f"every fan-out link failed: {errors[0]!r}")
            self.partial_failures += len(errors)

    def recv(self, timeout_s: float = 0.0) -> List[ShipFrame]:
        raise ReplTransportError("FanoutTransport is ship-side only; followers receive on their own link")

    def fence(self, epoch: int) -> None:
        super().fence(epoch)
        for link in self._links:
            try:
                link.fence(epoch)
            except Exception:  # noqa: BLE001 — best effort; each receive side re-checks
                pass

    def request_snapshot(self) -> None:
        pass  # ship-side only

    def take_snapshot_request(self) -> bool:
        pending = False
        for link in self._links:
            try:
                if link.take_snapshot_request():
                    pending = True
            except Exception:  # noqa: BLE001
                continue
        return pending

    def close(self) -> None:
        for link in self._links:
            try:
                link.close()
            except Exception:  # noqa: BLE001
                pass
