"""ReplicaApplier — the follower's receive/replay loop + staleness accounting.

One daemon thread per follower engine: receive frames from the transport,
bootstrap from the first applicable :class:`SnapshotFrame` through the engine's
existing restore path, then replay :class:`WalFrame` records *in seq order*
through the PR 4 replay machinery (chunk records re-walk the masked rows in
scan order; request records re-apply whole) — so the follower's accumulator
state is **bit-identical to the primary at every applied seq**. Out-of-order
protection is the seq chain itself: a duplicate (seq <= applied) is dropped, a
gap (seq > applied+1) parks replay and requests a fresh snapshot instead of
ever applying a record twice or out of order.

Staleness: the applier tracks ``known_seq`` (the primary's newest position it
has heard of, via WAL frames and heartbeats) and the LOCAL monotonic instant
it last learned it was current (frame wall stamps only order advancements —
never compared against this host's clock, so cross-host skew cannot shrink
the reported staleness). :meth:`lag` derives
:class:`~metrics_tpu.repl.config.ReplicaLag` from the two — conservative by
construction: a silent link GROWS ``seconds_behind`` rather than freezing it,
and the only optimism left is one link transit time.

Promotion support: :meth:`stop` halts the thread; :meth:`drain` applies
everything already shipped (the promoted follower serves exactly the acked
prefix); the engine then fences the transport at ``epoch + 1``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional

from metrics_tpu.obs import instrument as _obs
from metrics_tpu.obs.fleet import AGGREGATOR
from metrics_tpu.repl.config import ReplConfig, ReplicaLag
from metrics_tpu.repl.transport import HeartbeatFrame, ShipFrame, SnapshotFrame, WalFrame

__all__ = ["ReplicaApplier"]


class ReplicaApplier:
    """One follower's receive loop over a :class:`ReplTransport`."""

    def __init__(self, engine: Any, cfg: ReplConfig, *, telemetry: Any, engine_label: str = "0") -> None:
        self.cfg = cfg
        self.transport = cfg.transport
        self.epoch = int(cfg.epoch)  # newest primary epoch heard (fencing floor)
        self._engine = engine
        self._telemetry = telemetry
        self._engine_label = engine_label

        self.applied_seq = -1
        self.known_seq = -1
        # the lineage known_seq was learned from: positions are only comparable
        # within one epoch, so hearing a HIGHER-epoch frame resets known_seq to
        # that lineage's numbering, while frames of the tracked epoch just max
        self._known_epoch = int(cfg.epoch)
        self.bootstrapped = False
        self.caught_up_wall: Optional[float] = None  # newest primary stamp seen (ordering only)
        # LOCAL monotonic instant of the advancement: seconds_behind is the age
        # since this replica last learned it was current — never a difference
        # of two hosts' wall clocks, which skew could silently shrink below the
        # true staleness (the opposite of a conservative bound)
        self._caught_up_mono: Optional[float] = None
        self.last_error: Optional[BaseException] = None
        self.parked = False  # terminal: promotion flipped the engine writable
        self._gap = False
        self._last_snap_request = 0.0
        # serializes frame application between the poll thread and a promotion
        # drain (which stops the thread first, but belt-and-suspenders)
        self._apply_lock = threading.Lock()
        self._progress = threading.Condition()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="metrics-tpu-repl-apply", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------ lifecycle

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=timeout)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                frames = self.transport.recv(timeout_s=self.cfg.poll_interval_s)
                if frames:
                    self.apply_frames(frames)
                if self._gap or not self.bootstrapped:
                    # gapped — or never bootstrapped at all: a REPLACEMENT
                    # follower attaching after the shipper's attach-time
                    # snapshot was consumed (by a dead predecessor) would
                    # otherwise wait passively for the next checkpoint
                    # generation, discarding WAL frames the whole time
                    self._maybe_request_snapshot()
            except Exception as exc:  # noqa: BLE001 — a bad frame/transport blip must not kill replay
                self.last_error = exc

    def _maybe_request_snapshot(self) -> None:
        now = time.monotonic()
        if now - self._last_snap_request >= max(self.cfg.poll_interval_s, 0.05):
            self._last_snap_request = now
            self.transport.request_snapshot()

    # ------------------------------------------------------------------ replay

    def apply_frames(self, frames: List[ShipFrame]) -> None:
        applied = 0
        batch_clean = True
        with self._apply_lock:
            if self.parked:
                # promotion already flipped the engine writable: a straggling
                # poll-thread batch (stop()'s join can time out mid-compile)
                # must not replay old-primary records into the new lineage —
                # they would mutate promoted state unjournaled
                return
            for frame in frames:
                if frame.epoch < self.epoch:
                    # transport-level fencing is authoritative, but a follower
                    # that heard a newer epoch drops stragglers here too
                    self._telemetry.count("fenced_rejections")
                    continue
                if frame.epoch > self.epoch:
                    # a higher epoch is a NEW primary lineage (a replacement
                    # primary bumps ReplConfig.epoch; every resumed primary
                    # bumps too): its seq numbering is fresh, so park replay
                    # until that lineage's snapshot arrives rather than
                    # mistaking its records for duplicates
                    self.epoch = frame.epoch
                    if self.bootstrapped:
                        self._gap = True
                try:
                    if isinstance(frame, WalFrame):
                        applied += self._apply_wal(frame)
                    elif isinstance(frame, SnapshotFrame):
                        self._apply_snapshot(frame)
                    elif isinstance(frame, HeartbeatFrame):
                        fleet = getattr(frame, "fleet", None)  # old pickles lack the slot
                        if fleet is not None:
                            AGGREGATOR.ingest(fleet)
                        self._learn_known(frame.epoch, frame.last_seq)
                        if (
                            self.bootstrapped
                            and not self._gap
                            # gapped: applied and last_seq may be positions in
                            # two DIFFERENT lineages — old applied 10000 vs a
                            # replacement's last_seq 40 would stamp the broken
                            # replica fresh; freshness only moves on a whole chain
                            and self.applied_seq >= frame.last_seq
                        ):
                            self._advance_caught_up(frame.t_wall)
                except Exception as exc:  # noqa: BLE001 — recv is destructive:
                    # one bad frame (e.g. a snapshot that CRC-verifies on the
                    # shipper but fails decode here) must not discard the rest
                    # of the batch — the WAL frames behind it are gone from the
                    # transport. Count + remember; the seq chain parks on any
                    # resulting gap and the snapshot path re-requests.
                    self.last_error = exc
                    batch_clean = False
                    self._telemetry.count("apply_failures")
            if frames and batch_clean and self.bootstrapped and not self._gap:
                # a NON-EMPTY batch applied cleanly on a WHOLE chain: a
                # previously-recorded error is healed and health() stops
                # reporting DEGRADED. Clearing on a bare recv return would
                # wipe a persistent frame-level failure on the very next IDLE
                # poll; clearing while un-bootstrapped/gapped would let the
                # 1s heartbeats mask a snapshot that fails decode every 30s
                # checkpoint interval — a replica permanently unable to
                # bootstrap would read SERVING ~97% of the time. While the
                # chain is broken, only the snapshot that mends it (setting
                # bootstrapped, clearing the gap, in this same batch) lets a
                # clean batch clear the record.
                self.last_error = None
        if applied:
            self._telemetry.count("applied_records", applied)
            _obs.record_repl_applied(self._engine_label, applied)
            # bound the async replay pipeline at one recv batch: replay kernels
            # enqueue without blocking (throughput), but a reader forcing a
            # value must never wait out an unbounded chain of pending chunks
            self._engine._repl_quiesce()
        with self._progress:
            self._progress.notify_all()
        if _obs.OBS.enabled:
            lag = self.lag()
            _obs.set_repl_lag(self._engine_label, lag.seqs_behind, lag.seconds_behind)

    def _learn_known(self, epoch: int, seq: int) -> None:
        """Record a primary position. Positions are only comparable within one
        lineage: a HIGHER-epoch source resets known_seq to that lineage's
        numbering (and drops the old lineage's freshness ordering stamp — a
        dead primary's clock must not gate the new one's advancements), while
        a source at the tracked epoch just advances the max."""
        if epoch > self._known_epoch:
            self._known_epoch = epoch
            self.known_seq = seq
            self.caught_up_wall = None
        elif seq > self.known_seq:
            self.known_seq = seq

    def _adopt_lineage(self) -> None:
        # the snapshot LANDED (restore did not raise): the chain is whole again
        self._gap = False

    def _advance_caught_up(self, t_wall: float) -> None:
        # the frame's primary wall stamp only ORDERS advancements (an old
        # re-delivered frame must not refresh freshness); the age itself is
        # measured on this host's monotonic clock
        if self.caught_up_wall is None or t_wall >= self.caught_up_wall:
            self.caught_up_wall = t_wall
            self._caught_up_mono = time.monotonic()

    def _apply_snapshot(self, frame: SnapshotFrame) -> None:
        if (
            self.bootstrapped
            and not self._gap
            and not (frame.bootstrap and frame.seq > self.applied_seq)
        ):
            # intact seq chain: WAL replay already covers (in order) everything
            # this snapshot holds — even while LAGGING, restoring would throw
            # away state just to rebuild it, and a large state's repeated
            # restore can itself keep the replica behind. Snapshot restores are
            # for (re)bootstrap only: never-bootstrapped, gap-parked, or
            # new-lineage followers (an epoch bump sets _gap before dispatch).
            # The one exception is a BOOTSTRAP-flagged snapshot AHEAD of our
            # applied position: the shipper re-bootstrapped because rotation
            # GC'd records it never shipped, so the chain up to frame.seq will
            # never complete — the snapshot is the only way forward. (A
            # bootstrap ship at/behind our position is a rewind for a dead
            # predecessor: drop it and the rewound WAL duplicates after it.)
            return
        # seq accounting resets ONLY when the snapshot comes from a lineage
        # NEWER than the one known_seq was learned from (see _learn_known) —
        # never on a bare seq/epoch comparison against our applied position: a
        # same-lineage gap healed by a snapshot OLDER than applied (checkpoints
        # lag the WAL tail, so a requested re-bootstrap routinely lands behind
        # us), or a fresh attach whose heartbeats already taught us this
        # lineage's tip, must both KEEP the known position — wiping it would
        # transiently report the replica caught up while the records between
        # snapshot and the primary's real tip are still in flight, and bounded
        # reads would serve exactly the staleness they were configured to
        # refuse.
        if frame.data is None:
            # empty bootstrap: the primary's state at frame.seq IS fresh init —
            # also the only answer a wiped primary with no snapshot yet can
            # give a gapped follower (its WAL starts at 0, so reset + replay
            # reconverges); ignoring it would park the follower forever
            if self.bootstrapped:
                self._engine._repl_reset_state()
            self.bootstrapped = True
            self.applied_seq = frame.seq
            self._learn_known(frame.epoch, frame.seq)
            self._adopt_lineage()
            self._telemetry.count("snapshot_loads")
            return
        self._engine._repl_restore_snapshot(frame.data)
        self.applied_seq = frame.seq
        self._learn_known(frame.epoch, frame.seq)
        self.bootstrapped = True
        self._adopt_lineage()
        if self.applied_seq >= self.known_seq:
            # nothing newer heard: state is current through the ship instant
            self._advance_caught_up(frame.t_wall)
        self._telemetry.count("snapshot_loads")

    def _apply_wal(self, frame: WalFrame) -> int:
        self._learn_known(frame.epoch, frame.seq)
        if not self.bootstrapped or self._gap:
            # waiting for a (re-)bootstrap snapshot. Gapped MUST park too: a
            # replacement primary's restarted seq numbering means seq/applied
            # arithmetic compares positions from two different lineages — a
            # new-lineage record whose seq lands on applied+1 would otherwise
            # replay onto old-lineage state, silently diverging from both.
            return 0
        if frame.seq <= self.applied_seq:
            return 0  # duplicate (re-ship overlap): exactly-once, drop
        if frame.seq != self.applied_seq + 1:
            self._gap = True  # rotation/loss upstream: re-bootstrap, never skip
            return 0
        self._engine._repl_apply_record(frame.payload)
        self.applied_seq = frame.seq
        if self.applied_seq >= self.known_seq:
            # freshness only advances when CAUGHT UP: a replica chewing through
            # a deep backlog is serving old data however recently it applied a
            # record — advancing per record would read seconds_behind≈0 at
            # arbitrary real staleness, the opposite of the conservative bound
            self._advance_caught_up(frame.t_wall)
        return 1

    # ------------------------------------------------------------------ promotion

    def park(self) -> None:
        """Terminal: called by promotion AFTER the drain. From here every
        apply is a no-op — even if the poll thread outlived ``stop()``'s join
        timeout (wedged in a cold kernel compile), it can never replay
        old-primary records into the promoted, writable engine."""
        with self._apply_lock:
            self.parked = True

    def drain(self, timeout_s: float) -> None:
        """Apply everything already shipped: poll the transport until it stays
        empty (or ``timeout_s`` elapses). Called with the poll thread stopped."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        quiet = 0
        while time.monotonic() < deadline:
            frames = self.transport.recv(timeout_s=0.0)
            if frames:
                quiet = 0
                self.apply_frames(frames)
                continue
            quiet += 1
            if quiet >= 2:  # two consecutive empty polls: the tail is drained
                return
            time.sleep(min(0.01, self.cfg.poll_interval_s))

    # ------------------------------------------------------------------ staleness

    def lag(self) -> ReplicaLag:
        seqs = max(0, self.known_seq - self.applied_seq)
        if not self.bootstrapped or self._gap or self._caught_up_mono is None:
            # gapped: the chain is broken — applied and known may even be
            # positions in two different lineages, so no finite bound holds
            return ReplicaLag(seqs_behind=seqs, seconds_behind=float("inf"))
        return ReplicaLag(
            seqs_behind=seqs, seconds_behind=max(0.0, time.monotonic() - self._caught_up_mono)
        )

    def watermark(self) -> "tuple[int, int]":
        """``(epoch, applied_seq)`` — this follower's applied WAL position.

        The pair is the generation-safe watermark the query plane's result
        cache keys on: seq numbers are only comparable within one primary
        lineage, so a failover (new epoch, fresh numbering) can never be
        mistaken for "the seq has not advanced". Taken under the apply lock:
        a torn read across an epoch flip could pair the old lineage's epoch
        with the new lineage's seq numbering and alias a fresh position onto
        a cached one."""
        with self._apply_lock:
            return (int(self.epoch), int(self.applied_seq))

    def await_seq(self, seq: int, timeout_s: float = 10.0) -> bool:
        """Test/ops helper: block until ``applied_seq >= seq`` (True) or timeout."""
        deadline = time.monotonic() + timeout_s
        with self._progress:
            while self.applied_seq < seq:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._progress.wait(remaining)
        return True
