"""ReplConfig + ReplicaLag — the replication plane's wiring and staleness bound.

One frozen dataclass handed to ``StreamingEngine(replication=ReplConfig(...))``.
Role ``"primary"`` attaches a background :class:`~metrics_tpu.repl.shipper.Shipper`
(requires the durable state plane: ``checkpoint=CheckpointConfig(..., wal=True)``
is what produces the snapshot + WAL lineage the shipper publishes). Role
``"follower"`` makes the engine a read replica: it bootstraps from a shipped
snapshot, continuously replays shipped WAL records, refuses writes with
:class:`~metrics_tpu.repl.errors.NotPrimaryError`, and refuses reads whose
:class:`ReplicaLag` exceeds the configured ``max_staleness`` bound.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

__all__ = ["ReplConfig", "ReplicaLag"]

_ROLES = ("primary", "follower")


@dataclass(frozen=True)
class ReplicaLag:
    """How far behind the primary a follower's applied state is.

    - ``seqs_behind``: WAL records known shipped/journaled but not yet applied
      here (0 = caught up with everything this replica has heard of).
    - ``seconds_behind``: age of the replica's view — the replica's OWN
      monotonic time since it last learned it was current (``inf`` before
      bootstrap / before anything was heard). Never a cross-host wall-clock
      difference, so clock skew cannot under-report staleness; the only
      optimism is one link transit time. Heartbeats keep it near the
      heartbeat interval on an idle stream; a dead link makes it grow — the
      conservative reading a bounded-staleness contract needs.
    """

    seqs_behind: int
    seconds_behind: float

    def exceeds(self, max_seqs: Optional[int], max_seconds: Optional[float]) -> bool:
        if max_seqs is not None and self.seqs_behind > max_seqs:
            return True
        if max_seconds is not None and self.seconds_behind > max_seconds:
            return True
        return False


@dataclass(frozen=True)
class ReplConfig:
    """Replication wiring for one :class:`~metrics_tpu.engine.StreamingEngine`.

    Args:
        role: ``"primary"`` (ship) or ``"follower"`` (replay + read-only serve).
        transport: the :class:`~metrics_tpu.repl.transport.ReplTransport` frames
            travel over. The primary sends on it; the follower receives.
        ship_interval_s: primary ship-loop tick — how often new WAL tail records
            are published (the floor on follower lag under steady traffic).
        poll_interval_s: follower receive-loop tick.
        heartbeat_interval_s: primary liveness/position frames on an idle
            stream, so a caught-up follower's ``seconds_behind`` stays bounded.
        max_staleness_seqs / max_staleness_s: the read contract — a follower
            read whose :class:`ReplicaLag` exceeds either bound is refused with
            :class:`~metrics_tpu.repl.errors.StalenessExceeded`. ``None`` = no
            bound on that axis (both ``None`` = always serve, tagged with lag).
        epoch: this node's starting fencing token. A promoted follower adopts
            ``deposed primary's epoch + 1`` and fences the transport at it; a
            restarted promoted primary recovers its token from snapshot meta.
            Standing up a REPLACEMENT primary on a fresh directory requires
            bumping ``epoch`` past the old one's: the higher epoch tells
            followers the seq numbering restarted (they re-bootstrap instead
            of dropping the new lineage's records as duplicates).
        promote_checkpoint: the :class:`~metrics_tpu.engine.CheckpointConfig`
            lineage a promoted follower re-opens as its OWN durable state plane
            (fresh directory — never the deposed primary's). ``None`` leaves a
            promoted node serving without durability (warned).
        drain_timeout_s: how long a promotion waits for the shipped tail to
            drain out of the transport before fencing.
    """

    role: str
    transport: Any
    ship_interval_s: float = 0.05
    poll_interval_s: float = 0.05
    heartbeat_interval_s: float = 1.0
    max_staleness_seqs: Optional[int] = None
    max_staleness_s: Optional[float] = None
    epoch: int = 0
    promote_checkpoint: Optional[Any] = None  # engine CheckpointConfig
    drain_timeout_s: float = 5.0

    def __post_init__(self) -> None:
        if self.role not in _ROLES:
            raise ValueError(f"`role` must be one of {_ROLES}, got {self.role!r}")
        if self.transport is None:
            raise ValueError("`transport` is required (e.g. repl.LoopbackLink())")
        if self.ship_interval_s <= 0 or self.poll_interval_s <= 0:
            raise ValueError("`ship_interval_s` and `poll_interval_s` must be > 0")
        if self.heartbeat_interval_s <= 0:
            raise ValueError(f"`heartbeat_interval_s` must be > 0, got {self.heartbeat_interval_s}")
        if self.drain_timeout_s < 0:
            raise ValueError(f"`drain_timeout_s` must be >= 0, got {self.drain_timeout_s}")
        if self.max_staleness_seqs is not None and self.max_staleness_seqs < 0:
            raise ValueError(f"`max_staleness_seqs` must be >= 0, got {self.max_staleness_seqs}")
        if self.max_staleness_s is not None and self.max_staleness_s < 0:
            raise ValueError(f"`max_staleness_s` must be >= 0, got {self.max_staleness_s}")
        if self.epoch < 0:
            raise ValueError(f"`epoch` must be >= 0, got {self.epoch}")
