"""RetrievalPrecisionRecallCurve + RetrievalRecallAtFixedPrecision.

Reference parity: src/torchmetrics/retrieval/precision_recall_curve.py (per-query
precision/recall arrays for k=1..max_k, averaged over queries; empty queries filled per
``empty_target_action``; ``RetrievalRecallAtFixedPrecision`` post-processes the averaged
curve via ``_retrieval_recall_at_fixed_precision``).

TPU-native: the per-query curves are built with ONE scatter-add into a dense
``(num_queries, max_k)`` matrix followed by a cumsum along k — no host loop.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.retrieval.base import RetrievalMetric, group_by_query
from metrics_tpu.utils.data import dim_zero_cat


def _retrieval_recall_at_fixed_precision(
    precision: Array,
    recall: Array,
    top_k: Array,
    min_precision: float,
) -> Tuple[Array, Array]:
    """Max recall (and its k) among points with precision >= min_precision."""
    p = np.asarray(precision)
    r = np.asarray(recall)
    ks = np.asarray(top_k)
    try:
        max_recall, best_k = max((rr, kk) for pp, rr, kk in zip(p, r, ks) if pp >= min_precision)
    except ValueError:
        max_recall, best_k = 0.0, len(ks)
    if max_recall == 0.0:
        best_k = len(ks)
    return jnp.asarray(max_recall, dtype=jnp.float32), jnp.asarray(best_k, dtype=jnp.int32)


class RetrievalPrecisionRecallCurve(RetrievalMetric):
    """Averaged (over queries) precision@k / recall@k curve for k = 1..max_k.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.retrieval import RetrievalPrecisionRecallCurve
        >>> metric = RetrievalPrecisionRecallCurve(max_k=3)
        >>> metric.update(jnp.array([0.9, 0.2, 0.7, 0.4]), jnp.array([1, 0, 1, 1]),
        ...               indexes=jnp.array([0, 0, 1, 1]))
        >>> precision, recall, top_k = metric.compute()
        >>> top_k
        Array([1, 2, 3], dtype=int32)
    """

    higher_is_better = True

    def __init__(
        self,
        max_k: Optional[int] = None,
        adaptive_k: bool = False,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if max_k is not None and not (isinstance(max_k, int) and max_k > 0):
            raise ValueError("`max_k` has to be a positive integer or None")
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.max_k = max_k
        self.adaptive_k = adaptive_k

    def compute(self) -> Tuple[Array, Array, Array]:
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)

        g = group_by_query(indexes, preds, target)
        max_k = self.max_k if self.max_k is not None else int(jnp.max(g.n_per))
        q = g.num_queries

        # hits per (query, rank<max_k) cell, then cumulative along k
        in_k = g.rank < max_k
        rel = jnp.zeros((q, max_k), jnp.float32).at[g.seg, jnp.minimum(g.rank, max_k - 1)].add(
            g.target * in_k.astype(jnp.float32)
        )
        cum_rel = jnp.cumsum(rel, axis=1)

        ks = jnp.arange(1, max_k + 1, dtype=jnp.float32)[None, :]          # (1, K)
        if self.adaptive_k:
            denom_k = jnp.minimum(ks, g.n_per[:, None])                     # (Q, K)
        else:
            denom_k = jnp.broadcast_to(ks, (q, max_k))

        valid = g.pos_per > 0
        precision = jnp.where(valid[:, None], cum_rel / denom_k, 0.0)
        recall = jnp.where(valid[:, None], cum_rel / jnp.maximum(g.pos_per[:, None], 1.0), 0.0)

        if self.empty_target_action == "error":
            if bool(jnp.any(~valid)):
                raise ValueError("`compute` method was provided with a query with no positive target.")
            mask = jnp.ones_like(valid)
        elif self.empty_target_action == "pos":
            precision = jnp.where(valid[:, None], precision, 1.0)
            recall = jnp.where(valid[:, None], recall, 1.0)
            mask = jnp.ones_like(valid)
        elif self.empty_target_action == "neg":
            mask = jnp.ones_like(valid)   # rows already zeroed
        else:  # skip
            mask = valid

        count = jnp.maximum(mask.sum(), 1)
        maskf = mask.astype(jnp.float32)[:, None]
        avg_precision = (precision * maskf).sum(axis=0) / count
        avg_recall = (recall * maskf).sum(axis=0) / count
        top_k = jnp.arange(1, max_k + 1, dtype=jnp.int32)
        return avg_precision, avg_recall, top_k

    def _query_values(self, g):  # pragma: no cover - curve metric overrides compute
        raise NotImplementedError


class RetrievalRecallAtFixedPrecision(RetrievalPrecisionRecallCurve):
    """Highest recall@k whose precision@k clears ``min_precision``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.retrieval import RetrievalRecallAtFixedPrecision
        >>> metric = RetrievalRecallAtFixedPrecision(min_precision=0.5, max_k=3)
        >>> metric.update(jnp.array([0.9, 0.2, 0.7, 0.4]), jnp.array([1, 0, 1, 1]),
        ...               indexes=jnp.array([0, 0, 1, 1]))
        >>> max_recall, best_k = metric.compute()
        >>> (round(float(max_recall), 4), int(best_k))
        (1.0, 3)
    """

    def __init__(
        self,
        min_precision: float = 0.0,
        max_k: Optional[int] = None,
        adaptive_k: bool = False,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            max_k=max_k,
            adaptive_k=adaptive_k,
            empty_target_action=empty_target_action,
            ignore_index=ignore_index,
            **kwargs,
        )
        if not (isinstance(min_precision, float) and 0.0 <= min_precision <= 1.0):
            raise ValueError("`min_precision` has to be a positive float between 0 and 1")
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        precision, recall, top_k = super().compute()
        return _retrieval_recall_at_fixed_precision(precision, recall, top_k, self.min_precision)
