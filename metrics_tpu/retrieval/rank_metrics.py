"""Module retrieval metrics over the vectorised segment kernel.

Reference parity (one class per file in the reference):
- RetrievalMAP            — retrieval/average_precision.py
- RetrievalMRR            — retrieval/reciprocal_rank.py
- RetrievalPrecision      — retrieval/precision.py
- RetrievalRecall         — retrieval/recall.py
- RetrievalFallOut        — retrieval/fall_out.py (empty check on NEGATIVE targets,
  reference fall_out.py:97-131)
- RetrievalHitRate        — retrieval/hit_rate.py
- RetrievalRPrecision     — retrieval/r_precision.py
- RetrievalNormalizedDCG  — retrieval/ndcg.py (non-binary gains allowed)

Each `_query_values` is a closed-form expression over :class:`GroupedRanks` — one fused
XLA program for all queries (SURVEY §7.2 step 6: segment-op group-by instead of the
reference's host split loop).
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.retrieval.base import GroupedRanks, RetrievalMetric
from metrics_tpu.utils.compute import _safe_divide


def _validate_k(k: Optional[int]) -> None:
    if k is not None and not (isinstance(k, int) and k > 0):
        raise ValueError("`k` has to be a positive integer or None")


class RetrievalMAP(RetrievalMetric):
    """Mean Average Precision over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalMAP
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> metric = RetrievalMAP()
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(0.7916667, dtype=float32)
    """

    def _query_values(self, g: GroupedRanks) -> Array:
        prec_at_hit = g.cum_hits / (g.rank.astype(jnp.float32) + 1.0)
        ap_sum = g.segment_sum(prec_at_hit * g.target)
        return _safe_divide(ap_sum, g.pos_per)


class RetrievalMRR(RetrievalMetric):
    """Mean Reciprocal Rank over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalMRR
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> metric = RetrievalMRR()
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(0.75, dtype=float32)
    """

    def _query_values(self, g: GroupedRanks) -> Array:
        n = g.rank.shape[0]
        first_hit = g.segment_min(jnp.where(g.target > 0, g.rank, n).astype(jnp.int32))
        return jnp.where(g.pos_per > 0, 1.0 / (first_hit.astype(jnp.float32) + 1.0), 0.0)


class RetrievalPrecision(RetrievalMetric):
    """Precision@k; ``adaptive_k`` clamps k to each query's size.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalPrecision
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> metric = RetrievalPrecision(k=2)
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(0.5, dtype=float32)
    """

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        adaptive_k: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        _validate_k(k)
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.k = k
        self.adaptive_k = adaptive_k

    def _query_values(self, g: GroupedRanks) -> Array:
        if self.k is None:
            k_eff = g.n_per
        elif self.adaptive_k:
            k_eff = jnp.minimum(float(self.k), g.n_per)
        else:
            k_eff = jnp.full_like(g.n_per, float(self.k))
        relevant = g.segment_sum(g.target * g.k_mask(k_eff))
        return _safe_divide(relevant, k_eff)


class RetrievalRecall(RetrievalMetric):
    """Recall@k.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalRecall
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> metric = RetrievalRecall(k=2)
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(0.75, dtype=float32)
    """

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        _validate_k(k)
        self.k = k

    def _query_values(self, g: GroupedRanks) -> Array:
        k_eff = g.n_per if self.k is None else jnp.full_like(g.n_per, float(self.k))
        relevant = g.segment_sum(g.target * g.k_mask(k_eff))
        return _safe_divide(relevant, g.pos_per)


class RetrievalFallOut(RetrievalMetric):
    """Fall-out@k: retrieved-negative fraction of all negatives; lower is better.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalFallOut
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> metric = RetrievalFallOut(k=2)
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(0.5, dtype=float32)
    """

    higher_is_better = False
    _empty_on = "negatives"

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        _validate_k(k)
        self.k = k

    def _query_values(self, g: GroupedRanks) -> Array:
        k_eff = g.n_per if self.k is None else jnp.full_like(g.n_per, float(self.k))
        neg = 1.0 - g.target
        retrieved_neg = g.segment_sum(neg * g.k_mask(k_eff))
        return _safe_divide(retrieved_neg, g.neg_per)


class RetrievalHitRate(RetrievalMetric):
    """Hit rate@k: 1 if any relevant document in the top-k.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalHitRate
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> metric = RetrievalHitRate(k=2)
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        _validate_k(k)
        self.k = k

    def _query_values(self, g: GroupedRanks) -> Array:
        k_eff = g.n_per if self.k is None else jnp.full_like(g.n_per, float(self.k))
        hits = g.segment_sum(g.target * g.k_mask(k_eff))
        return (hits > 0).astype(jnp.float32)


class RetrievalRPrecision(RetrievalMetric):
    """Precision at k = (# relevant documents of the query).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalRPrecision
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> metric = RetrievalRPrecision()
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(0.75, dtype=float32)
    """

    def _query_values(self, g: GroupedRanks) -> Array:
        in_top_r = (g.rank.astype(jnp.float32) < g.pos_per[g.seg]).astype(jnp.float32)
        relevant = g.segment_sum(g.target * in_top_r)
        return _safe_divide(relevant, g.pos_per)


class RetrievalNormalizedDCG(RetrievalMetric):
    """nDCG@k with raw-gain DCG over possibly non-binary targets.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalNormalizedDCG
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> metric = RetrievalNormalizedDCG()
        >>> metric.update(preds, target, indexes=indexes)
        >>> round(float(metric.compute()), 4)
        0.8467
    """

    allow_non_binary_target = True

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        _validate_k(k)
        self.k = k

    def _query_values(self, g: GroupedRanks) -> Array:
        k_eff = g.n_per if self.k is None else jnp.minimum(float(self.k), g.n_per)
        mask = g.k_mask(k_eff)
        discount = 1.0 / jnp.log2(g.rank.astype(jnp.float32) + 2.0)
        dcg = g.segment_sum(g.target * discount * mask)
        idcg = g.segment_sum(g.ideal_target * discount * mask)
        return jnp.where(idcg > 0, _safe_divide(dcg, idcg), 0.0)
