from metrics_tpu.retrieval.base import GroupedRanks, RetrievalMetric, group_by_query
from metrics_tpu.retrieval.precision_recall_curve import (
    RetrievalPrecisionRecallCurve,
    RetrievalRecallAtFixedPrecision,
)
from metrics_tpu.retrieval.rank_metrics import (
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRecall,
    RetrievalRPrecision,
)

__all__ = [
    "GroupedRanks",
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMRR",
    "RetrievalMetric",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalPrecisionRecallCurve",
    "RetrievalRecall",
    "RetrievalRecallAtFixedPrecision",
    "RetrievalRPrecision",
    "group_by_query",
]
