"""RetrievalMetric base — vectorised group-by-query compute.

Reference parity: src/torchmetrics/retrieval/base.py:25 (``RetrievalMetric`` keeps
``indexes/preds/target`` list states; compute sorts by index, splits via
``_flexible_bincount`` and loops queries on host, applying ``empty_target_action``).

TPU-native redesign: NO host loop. One ``lexsort`` by (query, -score) orders every
document of every query; per-document within-query ranks and cumulative hit counts come
from cumulative ops; per-query reductions are ``jax.ops.segment_sum/min`` with a static
``num_segments``. Every retrieval metric is then a closed-form expression over these
arrays — a single fused XLA program over all queries instead of Q small kernels.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.utils.checks import _check_retrieval_inputs
from metrics_tpu.utils.data import dim_zero_cat


@dataclass
class GroupedRanks:
    """All per-document/per-query arrays needed by any ranked-retrieval metric.

    Sorted order is (query ascending, score descending). ``seg`` maps each document to
    a dense query id in [0, num_queries); ``rank`` is the 0-based position of the
    document within its query's ranking.
    """

    seg: Array          # (N,) int32 dense query ids, sorted
    rank: Array         # (N,) int32 within-query rank by descending score
    preds: Array        # (N,) float32, sorted
    target: Array       # (N,) float32, sorted by (query, -score)
    n_per: Array        # (Q,) float32 docs per query
    pos_per: Array      # (Q,) float32 positive-target total per query (sum of gains)
    neg_per: Array      # (Q,) float32 count of zero/negative targets per query
    cum_hits: Array     # (N,) float32 inclusive within-query cumsum of target
    num_queries: int
    # unsorted originals, kept so ideal_target can be derived on demand
    indexes_raw: Array
    target_raw: Array
    _ideal_cache: Optional[Array] = None

    @property
    def ideal_target(self) -> Array:
        """(N,) float32 gains sorted by (query, -target) — the ideal ranking for nDCG.

        Lazy: this is the only consumer of a second full lexsort, and only nDCG
        needs it — eagerly sorting here would tax every other retrieval metric
        with the most expensive op in the pipeline (~40% of end-to-end time at
        100k docs).
        """
        if self._ideal_cache is None:
            ideal_order = jnp.lexsort((-self.target_raw.astype(jnp.float32), self.indexes_raw))
            self._ideal_cache = self.target_raw[ideal_order].astype(jnp.float32)
        return self._ideal_cache

    def segment_sum(self, x: Array) -> Array:
        return jax.ops.segment_sum(x, self.seg, num_segments=self.num_queries)

    def segment_min(self, x: Array) -> Array:
        return jax.ops.segment_min(x, self.seg, num_segments=self.num_queries)

    def k_mask(self, k: Optional[Array]) -> Array:
        """(N,) mask selecting documents with rank < k (k per-query or scalar; None = all)."""
        if k is None:
            return jnp.ones_like(self.rank, dtype=jnp.float32)
        k_per_doc = k[self.seg] if getattr(k, "ndim", 0) == 1 else k
        return (self.rank < k_per_doc).astype(jnp.float32)


def group_by_query(indexes: Array, preds: Array, target: Array) -> GroupedRanks:
    """Build :class:`GroupedRanks` from flat (indexes, preds, target)."""
    n = preds.shape[0]
    order = jnp.lexsort((-preds, indexes))
    idx_s = indexes[order]
    preds_s = preds[order]
    tgt_s = target[order].astype(jnp.float32)

    new = jnp.concatenate([jnp.ones((1,), bool), idx_s[1:] != idx_s[:-1]])
    seg = jnp.cumsum(new.astype(jnp.int32)) - 1
    num_queries = int(seg[-1]) + 1 if n else 0

    positions = jnp.arange(n, dtype=jnp.int32)
    seg_start = jax.lax.cummax(jnp.where(new, positions, 0))
    rank = positions - seg_start

    # within-query inclusive cumsum of target: global cumsum minus the base at the
    # segment start (cummax trick requires non-negative targets, which retrieval has)
    pre = jnp.cumsum(tgt_s)
    excl = pre - tgt_s
    base = jax.lax.cummax(jnp.where(new, excl, 0.0))
    cum_hits = pre - base

    ones = jnp.ones((n,), jnp.float32)
    n_per = jax.ops.segment_sum(ones, seg, num_segments=num_queries)
    pos_per = jax.ops.segment_sum(tgt_s, seg, num_segments=num_queries)
    neg_per = jax.ops.segment_sum((tgt_s <= 0).astype(jnp.float32), seg, num_segments=num_queries)

    return GroupedRanks(
        seg=seg,
        rank=rank,
        preds=preds_s,
        target=tgt_s,
        n_per=n_per,
        pos_per=pos_per,
        neg_per=neg_per,
        cum_hits=cum_hits,
        num_queries=num_queries,
        indexes_raw=indexes,
        target_raw=target,
    )


class RetrievalMetric(Metric):
    """Base for retrieval metrics (reference retrieval/base.py:25).

    Subclasses implement :meth:`_query_values` returning one value per query; this base
    handles input validation, state, the vectorised grouping, and
    ``empty_target_action`` semantics (neg/pos/skip/error on queries with no positive —
    or, for fall-out, no negative — target).
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = True
    full_state_update: bool = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    # group_by_query materialises a data-dependent query count (int(seg[-1])), and the
    # empty_target_action="error" branch does a host bool — compute runs on host; the
    # cat-state sync itself still lowers to in-trace all_gather.
    _host_compute = True

    allow_non_binary_target: bool = False
    # which per-query count must be non-zero for the query to be "non-empty"
    _empty_on: str = "positives"

    indexes: List[Array]
    preds: List[Array]
    target: List[Array]

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        if empty_target_action not in ("error", "skip", "neg", "pos"):
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action

        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index

        self.add_state("indexes", default=[], dist_reduce_fx="cat")
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target = _check_retrieval_inputs(
            indexes, preds, target, allow_non_binary_target=self.allow_non_binary_target, ignore_index=self.ignore_index
        )
        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)

        groups = group_by_query(indexes, preds, target)
        values = self._query_values(groups)
        valid = (groups.pos_per if self._empty_on == "positives" else groups.neg_per) > 0

        if self.empty_target_action == "error":
            if bool(jnp.any(~valid)):
                kind = "positive" if self._empty_on == "positives" else "negative"
                raise ValueError(f"`compute` method was provided with a query with no {kind} target.")
            mask = jnp.ones_like(valid)
        elif self.empty_target_action == "pos":
            values = jnp.where(valid, values, 1.0)
            mask = jnp.ones_like(valid)
        elif self.empty_target_action == "neg":
            values = jnp.where(valid, values, 0.0)
            mask = jnp.ones_like(valid)
        else:  # skip
            mask = valid

        count = mask.sum()
        total = jnp.where(mask, values, 0.0).sum()
        return jnp.where(count > 0, total / jnp.maximum(count, 1), 0.0).astype(jnp.float32)

    def _query_values(self, groups: GroupedRanks) -> Array:
        """Return the metric value for every query as a (Q,) array."""
        raise NotImplementedError
