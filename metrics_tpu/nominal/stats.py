"""Nominal association module metrics (reference src/torchmetrics/nominal/{cramers,
pearson,tschuprows,theils_u}.py): joint ``confmat`` sum state + χ²-style compute."""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.nominal.stats import (
    _cramers_v_compute,
    _format_nominal,
    _pearsons_contingency_coefficient_compute,
    _theils_u_compute,
    _tschuprows_t_compute,
)
from metrics_tpu.functional.nominal.utils import _joint_confusion_matrix, _nominal_input_validation
from metrics_tpu.metric import Metric, zero_state


class _NominalBase(Metric):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    _host_compute = True  # empty-row/col dropping is data-dependent

    def __init__(
        self,
        num_classes: int,
        nan_strategy: str = "replace",
        nan_replace_value: Optional[float] = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(num_classes, int) or num_classes < 1:
            raise ValueError("Expected argument `num_classes` to be a positive integer")
        self.num_classes = num_classes
        _nominal_input_validation(nan_strategy, nan_replace_value)
        self.nan_strategy = nan_strategy
        self.nan_replace_value = nan_replace_value
        self.add_state("confmat", zero_state((num_classes, num_classes), jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _format_nominal(preds, target, self.nan_strategy, self.nan_replace_value)
        self.confmat = self.confmat + _joint_confusion_matrix(preds, target, self.num_classes, self.num_classes)


class CramersV(_NominalBase):
    """Cramér's V (reference nominal/cramers.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CramersV
        >>> a = jnp.array([0, 1, 2, 1, 0, 2, 1])
        >>> b = jnp.array([0, 1, 2, 1, 0, 2, 2])
        >>> metric = CramersV(num_classes=3)
        >>> metric.update(a, b)
        >>> round(float(metric.compute()), 4)
        0.7638
    """

    def __init__(self, num_classes: int, bias_correction: bool = True, **kwargs: Any) -> None:
        super().__init__(num_classes, **kwargs)
        self.bias_correction = bias_correction

    def compute(self) -> Array:
        return _cramers_v_compute(self.confmat, self.bias_correction)


class PearsonsContingencyCoefficient(_NominalBase):
    """Pearson's contingency coefficient (reference nominal/pearson.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PearsonsContingencyCoefficient
        >>> a = jnp.array([0, 1, 2, 1, 0, 2, 1])
        >>> b = jnp.array([0, 1, 2, 1, 0, 2, 2])
        >>> metric = PearsonsContingencyCoefficient(num_classes=3)
        >>> metric.update(a, b)
        >>> round(float(metric.compute()), 4)
        0.7687
    """

    def compute(self) -> Array:
        return _pearsons_contingency_coefficient_compute(self.confmat)


class TschuprowsT(_NominalBase):
    """Tschuprow's T (reference nominal/tschuprows.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import TschuprowsT
        >>> a = jnp.array([0, 1, 2, 1, 0, 2, 1])
        >>> b = jnp.array([0, 1, 2, 1, 0, 2, 2])
        >>> metric = TschuprowsT(num_classes=3)
        >>> metric.update(a, b)
        >>> round(float(metric.compute()), 4)
        0.7638
    """

    def __init__(self, num_classes: int, bias_correction: bool = True, **kwargs: Any) -> None:
        super().__init__(num_classes, **kwargs)
        self.bias_correction = bias_correction

    def compute(self) -> Array:
        return _tschuprows_t_compute(self.confmat, self.bias_correction)


class TheilsU(_NominalBase):
    """Theil's U (reference nominal/theils_u.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import TheilsU
        >>> a = jnp.array([0, 1, 2, 1, 0, 2, 1])
        >>> b = jnp.array([0, 1, 2, 1, 0, 2, 2])
        >>> metric = TheilsU(num_classes=3)
        >>> metric.update(a, b)
        >>> round(float(metric.compute()), 4)
        0.7472
    """

    def compute(self) -> Array:
        return _theils_u_compute(self.confmat)
