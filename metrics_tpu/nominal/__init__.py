"""Nominal association module metrics (reference src/torchmetrics/nominal/)."""

from metrics_tpu.nominal.stats import CramersV, PearsonsContingencyCoefficient, TheilsU, TschuprowsT

__all__ = ["CramersV", "PearsonsContingencyCoefficient", "TheilsU", "TschuprowsT"]
