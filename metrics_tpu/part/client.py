"""PartitionedClient — tenant-routed writes across a partitioned cluster.

Routing is two lookups deep: the :class:`~metrics_tpu.part.pmap.PartitionMap`
answers *which partition* owns a tenant (seeded ring + migration overrides),
and the partition's *named lease* answers *which node* leads that partition.
The second lookup is exactly the cluster plane's routing contract, so this
client composes one :class:`~metrics_tpu.cluster.client.ClusterClient` router
per partition, each scoped to its partition's named lease through a
``_LeaseView`` store adapter. Each router keeps its own leader cache, lease-
epoch memo, and capped jittered backoff — a failover on partition ``p3``
re-resolves ``p3``'s lease only; the other P-1 routing entries stay warm and
there is never a whole-map refresh storm.

Migration windows surface as
:class:`~metrics_tpu.guard.errors.TenantQuarantined` from the *source*
partition (the migration guard holds the tenant there). The client treats
that as a routing-table staleness signal: reload the partition map once,
and if the tenant's partition moved, retry at the new home; if it did not
move, the quarantine is real (mid-migration or genuinely poisonous) and
propagates to the caller.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Hashable, Mapping, Optional

from metrics_tpu.cluster.client import ClusterClient
from metrics_tpu.cluster.store import CoordStore, Lease, Member
from metrics_tpu.guard.errors import TenantQuarantined
from metrics_tpu.part.pmap import PartitionMap

__all__ = ["PartitionedClient"]


class _LeaseView:
    """A :class:`CoordStore` facade scoped to ONE named lease.

    ``ClusterClient`` speaks the default-lease API (``read_lease()``); the
    partition plane keys P leases by name in one store. This adapter binds a
    partition name into every lease read so an unmodified ``ClusterClient``
    routes against exactly its partition's leadership.
    """

    def __init__(self, store: CoordStore, name: str) -> None:
        self._store = store
        self._name = name

    def now(self) -> float:
        return self._store.now()

    def read_lease(self, name: str = "") -> Optional[Lease]:
        return self._store.read_lease(self._name)

    def members(self) -> Dict[str, Member]:
        return self._store.members()


class PartitionedClient:
    """Route tenant traffic across a partitioned cluster.

    ``engines`` maps node id → (partition id → engine handle): every node runs
    one engine per partition, and the per-partition router sees only the
    column of engines serving its partition. All ``ClusterClient`` knobs pass
    through; each partition's router gets a distinct derived ``rng_seed`` so
    replica picks and backoff jitter decorrelate across partitions.
    """

    def __init__(
        self,
        store: CoordStore,
        engines: Mapping[str, Mapping[int, Any]],
        *,
        pmap: Optional[PartitionMap] = None,
        partitions: Optional[int] = None,
        retries: int = 8,
        backoff_s: float = 0.02,
        backoff_cap_s: float = 0.5,
        sleep: Callable[[float], None] = time.sleep,
        rng_seed: Optional[int] = None,
        lease_reread_s: float = 0.25,
    ) -> None:
        if pmap is None:
            if partitions is None:
                raise ValueError("PartitionedClient needs pmap or partitions")
            pmap = PartitionMap(partitions)
        self.pmap = pmap
        self._store = store
        self._routers: Dict[int, ClusterClient] = {}
        for pid in range(pmap.partitions):
            name = pmap.name_of(pid)
            column = {
                node: node_engines[pid]
                for node, node_engines in engines.items()
                if pid in node_engines
            }
            self._routers[pid] = ClusterClient(
                _LeaseView(store, name),
                column,
                retries=retries,
                backoff_s=backoff_s,
                backoff_cap_s=backoff_cap_s,
                sleep=sleep,
                rng_seed=(rng_seed + pid) if rng_seed is not None else None,
                lease_reread_s=lease_reread_s,
            )

    # ------------------------------------------------------------------ resolve

    def router(self, pid: int) -> ClusterClient:
        return self._routers[pid]

    def partition_of(self, key: Hashable) -> int:
        return self.pmap.partition_of(key)

    def leader_of(self, pid: int, *, refresh: bool = False) -> Optional[str]:
        """The node currently leading partition ``pid`` (None mid-election)."""
        return self._routers[pid].leader_id(refresh=refresh)

    def routing_table(self) -> Dict[str, Optional[str]]:
        """Partition name → currently-resolved leader (cache state, not a
        fresh store sweep — exactly what the next request would use)."""
        return {
            self.pmap.name_of(pid): router.leader_id(refresh=False)
            for pid, router in self._routers.items()
        }

    @property
    def redirects(self) -> int:
        """Redirect bounces absorbed across ALL partitions' routers."""
        return sum(router.redirects for router in self._routers.values())

    # ------------------------------------------------------------------ routing

    def submit(self, key: Hashable, *args: Any, **kwargs: Any) -> Any:
        """Route one write to its tenant's partition leader."""
        pid = self.pmap.partition_of(key)
        try:
            return self._routers[pid].submit(key, *args, **kwargs)
        except TenantQuarantined:
            # possibly a migration hold at a stale routing entry: the tenant
            # may have moved partitions since our map snapshot. Reload once;
            # only a genuinely moved tenant earns a retry.
            self.pmap.reload()
            new_pid = self.pmap.partition_of(key)
            if new_pid == pid:
                raise
            return self._routers[new_pid].submit(key, *args, **kwargs)

    def compute(self, key: Hashable, *, prefer: str = "leader", **kwargs: Any) -> Any:
        """Route one read within the tenant's partition (leader truth or
        staleness-bounded replica, per ``prefer``)."""
        pid = self.pmap.partition_of(key)
        try:
            return self._routers[pid].compute(key, prefer=prefer, **kwargs)
        except TenantQuarantined:
            self.pmap.reload()
            new_pid = self.pmap.partition_of(key)
            if new_pid == pid:
                raise
            return self._routers[new_pid].compute(key, prefer=prefer, **kwargs)

    # ------------------------------------------------------------------ query plane

    def rollup(
        self, pid: int, *, prefer: str = "replica", window: bool = False
    ) -> "tuple[Any, str, bool]":
        """One partition's every-tenant fold (the global query's per-partition
        read), via the partition's redirect ladder. Returns the stamped
        :class:`~metrics_tpu.query.rollup.PartitionRollup` with its partition
        name and serving node filled in, plus ``(node, served_by_leader)``
        provenance."""
        from dataclasses import replace as _dc_replace

        ru, node, is_leader = self._routers[pid].call("rollup", prefer=prefer, window=window)
        # the engine stamps what it knows locally; the router knows the
        # cluster-level identity this rollup must be reported under
        ru = _dc_replace(ru, partition=self.pmap.name_of(pid), node=node)
        return ru, node, is_leader

    def wal_watermark(
        self, pid: int, *, prefer: str = "replica", retries: Optional[int] = None
    ) -> "tuple[tuple[int, int], str, bool]":
        """One partition's ``(epoch, seq)`` WAL watermark — the cache
        revalidation probe. Two ints over the read path, follower-servable,
        behind the same staleness gate as the rollup it vouches for."""
        return self._routers[pid].call("wal_watermark", prefer=prefer, retries=retries)
