"""PartitionMap — tenant → partition assignment, pinned in a manifest.

The partition plane's routing truth is the same seeded consistent-hash ring
the shard plane uses (:mod:`metrics_tpu.shard.ring` — PYTHONHASHSEED-
independent, stable across processes), plus two small tables the ring cannot
express:

- **overrides**: tenants moved by a live migration. A migrated tenant keeps
  its ring position (the ring is immutable for a fixed partition count) and
  is re-routed by an explicit ``stable_key_bytes``-keyed entry — committed
  atomically in the manifest as the migration's routing commit point.
- **epoch floors**: per-partition minimum election epochs. A migration into
  partition ``p`` records ``floor = current epoch + 1`` so no later leader of
  ``p`` can promote at-or-below the epoch the handoff happened in — frames
  from before the migration can never be confused with frames after it.

``partition_manifest.json`` mirrors the shard plane's ``shard_manifest.json``
contract: ring parameters (partitions/vnodes/seed) are pinned at first
construction and a restart with different parameters is a crash at
construction, never silent re-routing away from the WAL that holds a tenant.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Hashable, Optional

from metrics_tpu.shard.ring import DEFAULT_VNODES, HashRing, stable_key_bytes
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["PartitionMap", "partition_name"]

_MANIFEST = "partition_manifest.json"


def partition_name(pid: int) -> str:
    """The stable lease/link name for partition ``pid`` ("p0", "p1", ...).
    This string keys the named lease, the per-partition repl links, and the
    obs series label — alphanumeric by construction (see the coordination
    store's lease-name charset)."""
    return f"p{int(pid)}"


class PartitionMap:
    """Tenant → partition routing: seeded ring + migration overrides + floors.

    ``directory`` (optional) pins the map in ``partition_manifest.json`` —
    construction verifies ring parameters against an existing manifest (crash
    on mismatch) and loads its overrides/floors; :meth:`commit` atomically
    persists the current tables (the migration commit point). Without a
    directory the map is in-memory only (tests, repl-less topologies).
    """

    def __init__(
        self,
        partitions: int,
        *,
        vnodes: int = DEFAULT_VNODES,
        seed: int = 0,
        directory: Optional[str] = None,
    ) -> None:
        if int(partitions) < 1:
            raise MetricsTPUUserError(f"PartitionMap needs >= 1 partition, got {partitions}")
        self._partitions = int(partitions)
        self._vnodes = int(vnodes)
        self._seed = int(seed)
        self._ring = HashRing(self._partitions, vnodes=self._vnodes, seed=self._seed)
        self._lock = threading.Lock()
        self._overrides: Dict[str, int] = {}  # stable_key_bytes(key).hex() -> pid
        self._floors: Dict[str, int] = {}  # partition name -> min election epoch
        self.directory = directory
        if directory is not None:
            self._check_or_load_manifest()

    # ------------------------------------------------------------------ routing

    @property
    def partitions(self) -> int:
        return self._partitions

    @property
    def ring(self) -> HashRing:
        return self._ring

    def names(self) -> tuple:
        return tuple(partition_name(pid) for pid in range(self._partitions))

    def name_of(self, pid: int) -> str:
        if not 0 <= int(pid) < self._partitions:
            raise MetricsTPUUserError(
                f"partition {pid} out of range [0, {self._partitions})"
            )
        return partition_name(pid)

    def partition_of(self, key: Hashable) -> int:
        """The partition currently routing ``key``: override first, then ring."""
        hexkey = stable_key_bytes(key).hex()
        with self._lock:
            pid = self._overrides.get(hexkey)
        return pid if pid is not None else self._ring.shard_for(key)

    def epoch_floor(self, pid: int) -> int:
        with self._lock:
            return self._floors.get(partition_name(pid), 0)

    # ---------------------------------------------------------------- mutation

    def set_override(self, key: Hashable, pid: int) -> None:
        """Pin ``key`` to partition ``pid`` (a completed migration's routing).
        An override landing the key back on its ring partition is dropped —
        the table holds only genuine exceptions."""
        pid = int(pid)
        if not 0 <= pid < self._partitions:
            raise MetricsTPUUserError(
                f"partition {pid} out of range [0, {self._partitions})"
            )
        hexkey = stable_key_bytes(key).hex()
        with self._lock:
            if self._ring.shard_for(key) == pid:
                self._overrides.pop(hexkey, None)
            else:
                self._overrides[hexkey] = pid

    def clear_override(self, key: Hashable) -> None:
        with self._lock:
            self._overrides.pop(stable_key_bytes(key).hex(), None)

    def set_epoch_floor(self, pid: int, floor: int) -> None:
        """Raise partition ``pid``'s minimum election epoch (monotone: a lower
        floor never overwrites a higher one)."""
        name = self.name_of(pid)
        with self._lock:
            self._floors[name] = max(int(floor), self._floors.get(name, 0))

    # ---------------------------------------------------------------- manifest

    def _manifest_doc(self) -> Dict:
        return {
            "partitions": self._partitions,
            "vnodes": self._vnodes,
            "seed": self._seed,
            "overrides": dict(self._overrides),
            "epoch_floors": dict(self._floors),
        }

    def _check_or_load_manifest(self) -> None:
        path = os.path.join(self.directory, _MANIFEST)
        if not os.path.exists(path):
            self.commit()
            return
        with open(path, "r", encoding="utf-8") as fh:
            have = json.load(fh)
        want = (self._partitions, self._vnodes, self._seed)
        got = (int(have.get("partitions", 0)), int(have.get("vnodes", 0)), int(have.get("seed", 0)))
        if got != want:
            raise MetricsTPUUserError(
                f"partition manifest at {path} was written with "
                f"partitions={got[0]}, vnodes={got[1]}, seed={got[2]} but this map "
                f"was configured with partitions={want[0]}, vnodes={want[1]}, "
                f"seed={want[2]} — a changed ring strands tenants on partitions "
                "whose WAL no longer holds them"
            )
        with self._lock:
            self._overrides = {
                str(k): int(v) for k, v in (have.get("overrides") or {}).items()
            }
            self._floors = {
                str(k): int(v) for k, v in (have.get("epoch_floors") or {}).items()
            }

    def reload(self) -> None:
        """Re-read overrides/floors from the manifest (another process — a
        migration coordinator — may have committed since). No-op in-memory."""
        if self.directory is not None:
            self._check_or_load_manifest()

    def commit(self) -> None:
        """Atomically persist the map (tmp + fsync + rename) — the migration
        routing commit point. Raises without a directory."""
        if self.directory is None:
            raise MetricsTPUUserError("PartitionMap.commit() needs a manifest directory")
        os.makedirs(self.directory, exist_ok=True)
        path = os.path.join(self.directory, _MANIFEST)
        tmp = path + ".tmp"
        with self._lock:
            doc = self._manifest_doc()
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
