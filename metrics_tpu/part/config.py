"""PartConfig — one node's wiring into the partition plane."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from metrics_tpu.cluster.errors import ClusterConfigError
from metrics_tpu.cluster.store import CoordStore
from metrics_tpu.shard.ring import DEFAULT_VNODES

__all__ = ["PartConfig"]


@dataclass(frozen=True)
class PartConfig:
    """Wiring for one :class:`~metrics_tpu.part.node.PartitionedNode`.

    The single-lease :class:`~metrics_tpu.cluster.ClusterConfig` contract,
    generalised to ``partitions`` independent leaderships:

    - ``partitions`` / ``vnodes`` / ``seed`` parameterize the tenant →
      partition ring (``PartitionMap``) and MUST be stable across restarts of
      the same deployment (the partition manifest enforces this).
    - ``link_factory(src, dst, partition)`` returns the one-way repl
      transport node ``src`` ships partition ``partition``'s lineage to node
      ``dst`` over — one channel per (pair, partition), so fencing partition
      ``p3``'s link never touches ``p5``'s. ``None`` disables replication
      orchestration (leases + membership only).
    - ``manifest_directory`` pins the partition map on disk (migrations
      commit their routing here); ``None`` keeps it in-memory.

    Timing knobs are identical to ``ClusterConfig`` (store-clock seconds) and
    apply per partition: every named lease has ``lease_ttl_s``, renewals
    happen at half TTL, and election backoff gates each partition's candidacy
    independently. ``on_transition(partition, old_role, new_role)`` observes
    every per-partition role change.
    """

    node_id: str
    store: CoordStore
    partitions: int = 8
    peers: Sequence[str] = ()
    link_factory: Optional[Callable[[str, str, str], object]] = None
    vnodes: int = DEFAULT_VNODES
    seed: int = 0
    manifest_directory: Optional[str] = None
    lease_ttl_s: float = 3.0
    heartbeat_interval_s: float = 1.0
    suspect_after_s: float = 2.5
    confirm_after_s: float = 6.0
    tick_interval_s: float = 0.25
    election_backoff_s: float = 0.25
    backoff_cap_s: float = 2.0
    drain_timeout_s: float = 5.0
    rng_seed: Optional[int] = None
    on_transition: Optional[Callable[[str, str, str], None]] = None

    def __post_init__(self) -> None:
        if not self.node_id:
            raise ClusterConfigError("node_id must be a non-empty string")
        if self.partitions < 1:
            raise ClusterConfigError(f"partitions must be >= 1, got {self.partitions}")
        if self.node_id in self.peers:
            raise ClusterConfigError(f"peers must not include the node itself ({self.node_id!r})")
        if len(set(self.peers)) != len(self.peers):
            raise ClusterConfigError(f"duplicate peer ids: {list(self.peers)}")
        if self.lease_ttl_s <= 0:
            raise ClusterConfigError(f"lease_ttl_s must be > 0, got {self.lease_ttl_s}")
        if self.suspect_after_s > self.confirm_after_s:
            raise ClusterConfigError(
                f"suspect_after_s ({self.suspect_after_s}) must not exceed "
                f"confirm_after_s ({self.confirm_after_s})"
            )
