"""Live tenant migration between partitions — destination-first, crash-safe.

Moving a tenant between partitions is the shard plane's ``resize()`` copy
discipline applied to ONE tenant while both partitions keep serving:

1. **Quarantine the source.** The migration guard *holds* the tenant on the
   source engine (:meth:`TenantQuarantine.hold`) so writes routed by a stale
   map refuse loudly (:class:`TenantQuarantined`) instead of mutating state
   that is about to move — the snapshot taken next is the final word.
2. **Snapshot through the checkpoint container.** ``export_tenant(retire=
   False)`` → ``ckpt_format.dumps`` → ``loads`` → ``import_tenant``: the
   same bytes a crash-recovery would restore, so the destination copy is
   bit-identical by construction — live segment AND window ring rows.
3. **Destination durability, then routing, then source eviction.** The
   destination checkpoints first; only then does the partition map commit
   the override (+ a bumped epoch floor for the destination partition) —
   THE commit point — and only after that does the source evict and
   checkpoint. A crash at any prefix leaves either (a) no routing change
   and an intact source (the hold is in-memory and dies with the process),
   or (b) committed routing and a possibly-surviving double copy, which
   :func:`sweep_partitions` resolves in the destination's favour on
   recovery — exactly the shard ``resize()`` argument.

The epoch-floor bump closes the fencing seam: the destination partition's
next election must land strictly above the epoch the handoff happened in, so
no pre-migration frame of the destination lineage can be confused with the
migrated tenant's post-migration writes.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, Mapping, Union

from metrics_tpu.ckpt import format as ckpt_format
from metrics_tpu.obs import instrument as _obs
from metrics_tpu.part.pmap import PartitionMap
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["migrate_tenant", "sweep_partitions"]


def _quarantine_of(engine: Any):
    guard = getattr(engine, "_guard", None)
    return getattr(guard, "quarantine", None) if guard is not None else None


def _checkpoint_engine(engine: Any):
    """Snapshot WITHOUT ``checkpoint_now()``'s whole-engine flush barrier.

    The migration's durable artifacts — the destination's ``b"P"`` import
    record and the source's ``b"T"`` retirement record — are WAL-journaled
    synchronously under the dispatch lock, so ``_checkpoint_view`` taken right
    after them is consistent and already reflects the move. A full flush here
    would wait for every NEIGHBOURING tenant's traffic to drain, which never
    happens on a partition under sustained load. Returns the generation, or
    ``None`` when checkpointing is off / quarantined / the write failed.
    """
    writer = getattr(engine, "_ckpt_writer", None)
    if writer is None or getattr(engine, "_quarantined", False):
        return None
    return writer.checkpoint_sync(engine._checkpoint_view)


def _engine_knows(engine: Any, key: Hashable) -> bool:
    """Whether ``key`` is resident on ``engine`` (slab or any tier) — the
    same membership test :func:`sweep_partitions` uses, no export needed."""
    if key in engine._keyed.keys:
        return True
    tier = getattr(engine, "_tier", None)
    return tier is not None and key in set(tier.keys())


def _plan_doc(
    key: Hashable,
    src_pid: int,
    dst_pid: int,
    *,
    pmap: PartitionMap,
    src_engine: Any,
    dst_engine: Any,
) -> Dict[str, Any]:
    """The validated migration plan, journal-shaped (what WOULD happen)."""
    return {
        "what": "migration_plan",
        "tenant": repr(key),
        "src_pid": src_pid,
        "dst_pid": dst_pid,
        "src_writable": not getattr(src_engine, "_repl_follower", False),
        "dst_writable": not getattr(dst_engine, "_repl_follower", False),
        "tenant_known_to_source": _engine_knows(src_engine, key),
        "quarantine_hold": _quarantine_of(src_engine) is not None,
        "dst_checkpointed_first": getattr(dst_engine, "_ckpt_writer", None) is not None,
        # the floor the commit would record: strictly above the epoch the
        # handoff would happen under, so no later dst election can promote
        # at-or-below it
        "epoch_floor": int(getattr(dst_engine, "_repl_epoch", 0)) + 1,
        "commit": "manifest" if pmap.directory is not None else "memory",
    }


def migrate_tenant(
    key: Hashable,
    dst_pid: int,
    *,
    pmap: PartitionMap,
    src_engine: Any,
    dst_engine: Any,
    node_id: str = "",
    dry_run: bool = False,
) -> Union[bool, Dict[str, Any]]:
    """Move tenant ``key`` to partition ``dst_pid``, live and bit-identically.

    ``src_engine`` / ``dst_engine`` are the writable *leaders* of the tenant's
    current and destination partitions (callers resolve leadership; this
    function enforces the copy/commit ordering). Returns False if the tenant
    already routes to ``dst_pid`` (no-op), True on a completed migration.
    Raises :class:`MetricsTPUUserError` if the source does not know the
    tenant. On failure before the map commit, the source hold is released and
    nothing has changed durably.

    ``dry_run=True`` validates the full plan — source/destination
    writability, tenant residency, quarantine hold availability, the epoch
    floor the commit would record, and where the routing would commit — and
    returns it as a dict WITHOUT executing anything (no hold is taken, no
    state moves). A ``plan["valid"]`` of True means the same call without
    ``dry_run`` would proceed past every precondition; the autopilot journals
    exactly this document before acting, and operators get a free "what would
    move" probe.
    """
    dst_pid = int(dst_pid)
    src_pid = pmap.partition_of(key)
    if src_pid == dst_pid:
        if dry_run:
            return {
                "what": "migration_plan", "tenant": repr(key),
                "src_pid": src_pid, "dst_pid": dst_pid,
                "noop": True, "valid": False,
                "why": "tenant already routes to the destination partition",
            }
        return False
    pmap.name_of(dst_pid)  # range check before any side effect

    if dry_run:
        plan = _plan_doc(key, src_pid, dst_pid, pmap=pmap,
                         src_engine=src_engine, dst_engine=dst_engine)
        plan["noop"] = False
        plan["valid"] = bool(
            plan["src_writable"] and plan["dst_writable"]
            and plan["tenant_known_to_source"]
        )
        if not plan["valid"]:
            plan["why"] = (
                "source is not writable" if not plan["src_writable"]
                else "destination is not writable" if not plan["dst_writable"]
                else "tenant is unknown to its partition leader"
            )
        return plan

    quarantine = _quarantine_of(src_engine)
    if quarantine is not None:
        quarantine.hold(key)
    try:
        # everything accepted so far FOR THIS TENANT lands in the exported
        # state. The hold above stops new rows for the key, so a per-tenant
        # drain suffices — a whole-engine flush() barrier never clears while
        # neighbouring tenants keep the source busy, and a live migration
        # must not require a quiet engine.
        src_engine.drain_tenant(key)
        entry = src_engine.export_tenant(key, retire=False)
        if entry is None:
            raise MetricsTPUUserError(
                f"tenant {key!r} is unknown to its partition p{src_pid} leader — "
                "nothing to migrate"
            )
        # bit-identical by construction: the exact bytes recovery would restore
        blob = ckpt_format.dumps(entry)
        dst_engine.import_tenant(key, ckpt_format.loads(blob).tree)
        if getattr(dst_engine, "_ckpt_writer", None) is not None:
            if _checkpoint_engine(dst_engine) is None:
                raise MetricsTPUUserError(
                    f"destination partition p{dst_pid} checkpoint failed — "
                    "migration aborted before the routing commit"
                )
        # fencing seam: the destination's next election must outrank the epoch
        # this handoff happened under
        floor = int(getattr(dst_engine, "_repl_epoch", 0)) + 1
        pmap.set_epoch_floor(dst_pid, floor)
        pmap.set_override(key, dst_pid)
        if pmap.directory is not None:
            pmap.commit()  # THE commit point: routing now names the destination
    except BaseException:
        # pre-commit failure: un-hold so the source keeps serving untouched
        if quarantine is not None:
            quarantine.release(key)
        raise
    # post-commit: the destination owns the tenant; retire the source copy.
    # A crash in here leaves a routed-away double copy for sweep_partitions.
    src_engine.evict_tenant(key)
    _checkpoint_engine(src_engine)
    # the hold STAYS on the source: a client still routing on a stale map
    # must refuse loudly (TenantQuarantined -> map reload) rather than
    # silently re-create the evicted tenant at init state. One held entry per
    # migrated-away tenant is the price of that refusal.
    shipper = getattr(dst_engine, "_shipper", None)
    if shipper is not None:
        # followers of the destination partition re-bootstrap so the imported
        # tenant reaches the replica set as a snapshot, not a mid-stream gap
        shipper._need_snapshot = True
    _obs.record_part_migration(node_id)
    return True


def sweep_partitions(pmap: PartitionMap, engines: Mapping[int, Any]) -> int:
    """Evict tenants that no longer route to the partition holding them.

    The recovery half of the migration crash argument: if the process died
    between the map commit and the source eviction, the source WAL still
    replays the migrated tenant. The committed map is the truth — any tenant
    whose :meth:`PartitionMap.partition_of` disagrees with its resident
    partition is a superseded double copy and is evicted (the destination's
    copy was durable before the commit, by ordering). Run over writable
    engines after recovery. Returns the number of evictions.
    """
    evicted = 0
    for pid, engine in engines.items():
        keys = list(engine._keyed.keys)
        tier = getattr(engine, "_tier", None)
        if tier is not None:
            keys.extend(tier.keys())
        for key in keys:
            if pmap.partition_of(key) != pid:
                engine.evict_tenant(key)
                evicted += 1
    return evicted
