"""Partition plane — multi-leader keyspace partitioning.

The cluster plane scales *availability* (one writable leader survives any
single host); it cannot scale *writes* — one leader means one host's WAL
bandwidth no matter how many hosts join. This plane splits the tenant
keyspace into P partitions on a seeded consistent-hash ring and runs the
cluster plane's leadership machinery once per partition: every partition has
its own named CAS-with-TTL lease, its own monotone fencing epoch, its own
``StreamingEngine`` WAL/ckpt lineage, and its own follower set. N hosts lead
~P/N partitions each, so aggregate write throughput scales with hosts while
every per-partition guarantee (at-most-one-writer, exactly-once
order-preserving replication, fenced zombie leaders) holds unchanged::

    from metrics_tpu.part import PartConfig, PartitionMap, PartitionedClient, PartitionedNode
    from metrics_tpu.cluster import DirectoryCoordStore
    from metrics_tpu.repl import DirectoryTransport

    store = DirectoryCoordStore("/shared/coord")
    link = lambda src, dst, part: DirectoryTransport(f"/shared/links/{src}-{dst}-{part}")
    node = PartitionedNode(engines_by_pid, PartConfig(
        node_id="a", peers=("b", "c"), store=store, partitions=8, link_factory=link))

    client = PartitionedClient(store, {"a": a_engines, "b": b_engines, "c": c_engines},
                               pmap=node.pmap)
    client.submit(key, preds, target)   # routed to key's partition's leader

Killing a host that leads k partitions triggers k *independent* failovers —
each a ranked election over that partition's bootstrapped followers — and the
blast radius of any one failover is one partition's tenants, not the fleet.
Tenants move between partitions live (:func:`migrate_tenant`): quarantined on
the source, shipped bit-identically through the checkpoint container, and
handed off destination-first so a crash at any point is recoverable. See
``docs/source/partitions.md`` for the at-most-one-writer-per-partition
argument and the migration walkthrough.
"""

from metrics_tpu.part.client import PartitionedClient
from metrics_tpu.part.config import PartConfig
from metrics_tpu.part.migrate import migrate_tenant, sweep_partitions
from metrics_tpu.part.node import PartitionedNode
from metrics_tpu.part.pmap import PartitionMap, partition_name

__all__ = [
    "PartConfig",
    "PartitionMap",
    "PartitionedClient",
    "PartitionedNode",
    "migrate_tenant",
    "partition_name",
    "sweep_partitions",
]
