"""PartitionedNode — one host supervising a SET of partition leaderships.

The cluster plane's :class:`~metrics_tpu.cluster.node.ClusterNode` runs one
lease, one engine, one lineage. This supervisor generalises that loop to P
keyspace partitions: one engine (own ``StreamingEngine`` WAL/ckpt lineage)
per partition, one *named* lease per partition, and the same three loops —
membership, failure detection, lead-or-elect — run once per tick with the
lease/election state tracked per partition:

1. **Membership.** One heartbeat record per node per interval (NOT per
   partition — P leases share one membership table), carrying a ``parts``
   payload: per-partition ``{bootstrapped, lag, role, health}``, the
   election's ranking input.
2. **Failure detection.** Identical to the cluster plane: a silent peer is
   suspected once per silence episode and confirmed dead past the threshold.
   One dead host does not produce one big failover — it produces ~P/N small,
   independent ones, each racing only that partition's named lease.
3. **Per-partition failover.** For every partition this node follows: read
   the named lease; if vacant, run the cluster plane's ranked election
   scoped to that partition (eligible = that partition's engine bootstrapped
   + SERVING; favourite = lowest lag over peers' ``parts`` records, ties by
   node id; non-favourites hold back one jittered round). The winner
   promotes that engine at exactly the won lease epoch — aligned the same
   way ``ClusterNode._align_epoch`` aligns the single lease — and ships the
   partition's new lineage over per-partition fan-out links. Losing a lease
   steps down exactly one partition; the node's other leaderships never
   notice.

At-most-one-writer holds *per partition* for the same boundary reason as the
cluster plane: partition ``p``'s lease epoch IS ``p``'s repl fencing epoch,
so a deposed owner's late shipments die at ``p``'s transport fence while its
still-held partitions keep shipping untouched (see docs/source/partitions.md).
"""

from __future__ import annotations

import random
import threading
from dataclasses import replace as _dc_replace
from typing import Any, Dict, List, Mapping, Optional, Tuple

from metrics_tpu.cluster.errors import ClusterConfigError, CoordStoreError
from metrics_tpu.cluster.store import Lease, Member
from metrics_tpu.obs import instrument as _obs
from metrics_tpu.obs.fleet import AGGREGATOR, node_snapshot
from metrics_tpu.obs.registry import OBS as _OBS
from metrics_tpu.part.config import PartConfig
from metrics_tpu.part.pmap import PartitionMap
from metrics_tpu.repl.errors import NotPromotableError
from metrics_tpu.repl.transport import FanoutTransport
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["PartitionedNode"]


class _PartSlot:
    """Per-partition supervisor state — the fields ClusterNode keeps once,
    kept once per partition."""

    __slots__ = (
        "pid",
        "name",
        "role",
        "lease",
        "following",
        "election_backoff",
        "next_attempt",
        "promote_backoff",
        "failovers",
        "lease_renewals",
    )

    def __init__(self, pid: int, name: str, role: str) -> None:
        self.pid = pid
        self.name = name
        self.role = role
        self.lease: Optional[Lease] = None
        self.following: Optional[str] = None
        self.election_backoff = 0.0
        self.next_attempt = float("-inf")
        self.promote_backoff = 0.0
        self.failovers = 0
        self.lease_renewals = 0


class PartitionedNode:
    """Supervise P partition engines' leaderships on one host.

    ``engines`` maps partition id → that partition's
    :class:`~metrics_tpu.engine.StreamingEngine` on THIS host (every host
    runs one engine per partition; which hosts lead which partitions is
    decided by the named-lease CAS). ``start=True`` runs a supervisor thread
    at ``cfg.tick_interval_s``; ``start=False`` leaves ticking to the caller
    (deterministic tests drive :meth:`tick` under a manual store clock).
    """

    def __init__(
        self,
        engines: Mapping[int, Any],
        cfg: PartConfig,
        *,
        pmap: Optional[PartitionMap] = None,
        start: bool = True,
    ) -> None:
        if set(engines) != set(range(cfg.partitions)):
            raise ClusterConfigError(
                f"engines must cover exactly partitions 0..{cfg.partitions - 1}, "
                f"got {sorted(engines)}"
            )
        for eng in engines.values():
            if getattr(eng, "_cluster", None) is not None:
                raise ClusterConfigError("engine already supervised by another node")
        self._engines: Dict[int, Any] = dict(engines)
        self.cfg = cfg
        self._store = cfg.store
        self.pmap = pmap if pmap is not None else PartitionMap(
            cfg.partitions,
            vnodes=cfg.vnodes,
            seed=cfg.seed,
            directory=cfg.manifest_directory,
        )
        if self.pmap.partitions != cfg.partitions:
            raise ClusterConfigError(
                f"pmap has {self.pmap.partitions} partitions, cfg says {cfg.partitions}"
            )
        self._rng = random.Random(
            cfg.rng_seed if cfg.rng_seed is not None else hash(cfg.node_id)
        )
        self._tick_lock = threading.Lock()
        self._slots: Dict[int, _PartSlot] = {}
        for pid in range(cfg.partitions):
            role = "leader" if self._engine_is_writable(pid) else "follower"
            slot = _PartSlot(pid, self.pmap.name_of(pid), role)
            self._slots[pid] = slot
            self._engines[pid]._cluster = self
            # hot-spot attribution without client-side joins: the adopted
            # engine's write-rate/backlog/latency series all carry its
            # partition name from here on (the shard plane's shard= parity)
            telemetry = getattr(self._engines[pid], "telemetry", None)
            if telemetry is not None:
                telemetry.add_labels(partition=slot.name)
            _obs.set_part_role(cfg.node_id, slot.name, role)

        self.suspicions = 0
        self.last_error: Optional[BaseException] = None
        self._suspected: Dict[str, float] = {}  # peer -> suspected-since (store time)
        self._last_heartbeat = float("-inf")

        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._run, name=f"metrics-tpu-part-{cfg.node_id}", daemon=True
            )
            self._thread.start()

    # ------------------------------------------------------------------ lifecycle

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as exc:  # noqa: BLE001 — the supervisor must outlive any one bad tick
                self.last_error = exc
            self._stop.wait(self.cfg.tick_interval_s)

    def close(self, *, release: bool = True) -> None:
        """Stop supervising. ``release=True`` steps every held lease down so
        peers can take the partitions over immediately instead of waiting out
        the TTLs."""
        self._stop.set()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=5.0)
        if release:
            for slot in self._slots.values():
                if slot.role == "leader":
                    try:
                        self._store.release_lease(self.cfg.node_id, name=slot.name)
                    except CoordStoreError:
                        pass  # unreachable store: the TTL is the fallback
        for eng in self._engines.values():
            if getattr(eng, "_cluster", None) is self:
                eng._cluster = None

    # ------------------------------------------------------------------ engine view

    def engine_for(self, pid: int) -> Any:
        return self._engines[pid]

    def owned(self) -> Tuple[int, ...]:
        """Partition ids this node currently leads."""
        return tuple(pid for pid, s in self._slots.items() if s.role == "leader")

    def _engine_is_writable(self, pid: int) -> bool:
        return not getattr(self._engines[pid], "_repl_follower", False)

    def _engine_view(self, pid: int) -> Tuple[str, bool, int]:
        """(health state, bootstrapped, lag_seqs) for one partition's engine."""
        eng = self._engines[pid]
        try:
            state = eng.health()["state"]
        except Exception:  # noqa: BLE001 — an unreadable engine is not SERVING
            state = "QUARANTINED"
        if not getattr(eng, "_repl_follower", False):
            return state, True, 0  # a primary (or repl-less engine) is its own truth
        applier = getattr(eng, "_applier", None)
        if applier is None:
            return state, False, -1  # demoted but not yet attached to a lineage
        lag = applier.lag()
        lag_seqs = int(lag.seqs_behind) if applier.bootstrapped and not applier._gap else -1
        return state, bool(applier.bootstrapped), lag_seqs

    # ------------------------------------------------------------------ the tick

    def tick(self) -> None:
        """One supervisor pass over every partition: heartbeat, detect, then
        lead-or-elect per partition. Store failures are absorbed and treated
        as lease loss, never success."""
        with self._tick_lock:
            now = self._store.now()
            views = {pid: self._engine_view(pid) for pid in self._slots}
            self._publish_heartbeat(now, views)
            self._detect_failures(now)
            for pid, slot in self._slots.items():
                if slot.role == "leader":
                    self._lead_part(now, slot)
                else:
                    self._follow_part(now, slot, views[pid])

    # ------------------------------------------------------------------ membership

    def _publish_heartbeat(self, now: float, views: Dict[int, Tuple[str, bool, int]]) -> None:
        if now - self._last_heartbeat < self.cfg.heartbeat_interval_s:
            return
        parts = {
            self._slots[pid].name: {
                "bootstrapped": bool(views[pid][1]),
                "lag": int(views[pid][2]),
                "role": self._slots[pid].role,
                "health": views[pid][0],
            }
            for pid in self._slots
        }
        healths = [v[0] for v in views.values()]
        worst = next((h for h in healths if h != "SERVING"), "SERVING")
        lags = [v[2] for v in views.values()]
        fleet = None
        if _OBS.enabled:
            try:
                # piggyback this node's telemetry snapshot on the membership
                # record it already publishes (cluster-plane parity) — the
                # autopilot reads these off the member table to observe
                fleet = node_snapshot(self.cfg.node_id)
            except Exception:  # noqa: BLE001 — telemetry must not break membership
                fleet = None
        member = Member(
            node_id=self.cfg.node_id,
            role="leader" if any(s.role == "leader" for s in self._slots.values()) else "follower",
            health=worst,
            bootstrapped=all(v[1] for v in views.values()),
            lag_seqs=-1 if any(l < 0 for l in lags) else max(lags, default=0),
            heartbeat=now,
            parts=parts,
            fleet=fleet,
        )
        try:
            self._store.heartbeat(member)
            self._last_heartbeat = now
        except CoordStoreError as exc:
            self.last_error = exc

    def _detect_failures(self, now: float) -> None:
        try:
            members = self._store.members()
        except CoordStoreError as exc:
            self.last_error = exc
            return
        if _OBS.enabled and any(s.role == "leader" for s in self._slots.values()):
            # any partition leader is a fleet merge point (cluster-plane
            # parity): fold peers' piggybacked snapshots off the member table
            # this pass already fetched — zero extra store IO
            AGGREGATOR.ingest_members(members.values())
        for peer in self.cfg.peers:
            rec = members.get(peer)
            silent = now - rec.heartbeat if rec is not None else float("inf")
            if rec is not None and silent >= self.cfg.suspect_after_s:
                if peer not in self._suspected:
                    # suspicion counts once per silence episode, on the edge
                    self._suspected[peer] = now
                    self.suspicions += 1
            elif rec is not None:
                self._suspected.pop(peer, None)

    def _confirmed_dead(self, now: float, rec: Optional[Member]) -> bool:
        return rec is None or now - rec.heartbeat >= self.cfg.confirm_after_s

    # ------------------------------------------------------------------ leading

    def _lease_floor(self, slot: _PartSlot) -> int:
        eng = self._engines[slot.pid]
        return max(
            int(getattr(eng, "_repl_epoch", 0)), 1, self.pmap.epoch_floor(slot.pid)
        )

    def _lead_part(self, now: float, slot: _PartSlot) -> None:
        cfg = self.cfg
        lease = slot.lease
        if lease is None or lease.remaining(now) <= cfg.lease_ttl_s / 2.0:
            try:
                renewed = self._store.acquire_lease(
                    cfg.node_id,
                    cfg.lease_ttl_s,
                    epoch_floor=self._lease_floor(slot),
                    name=slot.name,
                )
            except CoordStoreError as exc:
                self.last_error = exc
                renewed = None
            if renewed is not None:
                if slot.lease is not None and renewed.epoch == slot.lease.epoch:
                    slot.lease_renewals += 1
                slot.lease = renewed
                self._align_epoch(slot, renewed)
                return
            # renewal failed: still covered until OUR deadline passes — after
            # that, assume deposed (a peer may already hold a newer epoch)
            if lease is not None and not lease.expired(now):
                return
            self._step_down_part(now, slot)

    def _align_epoch(self, slot: _PartSlot, lease: Lease) -> None:
        """Make this partition's lease epoch and shipping epoch ONE fact —
        the per-partition twin of ``ClusterNode._align_epoch``."""
        eng = self._engines[slot.pid]
        if not self._engine_is_writable(slot.pid):
            return
        if int(getattr(eng, "_repl_epoch", 0)) == lease.epoch:
            return
        eng._repl_epoch = lease.epoch
        shipper = getattr(eng, "_shipper", None)
        if shipper is not None:
            shipper.epoch = lease.epoch
            shipper._need_snapshot = True  # followers re-bootstrap into the new epoch

    def _step_down_part(self, now: float, slot: _PartSlot) -> None:
        """Lease lost for ONE partition: stop writing it, rejoin whatever
        lineage the store names — the node's other partitions never notice."""
        self._transition(slot, "follower")
        slot.lease = None
        slot.next_attempt = now + self._jitter(self.cfg.election_backoff_s)
        _obs.record_part_lease_lost(self.cfg.node_id, slot.name)
        try:
            current = self._store.read_lease(slot.name)
        except CoordStoreError as exc:
            self.last_error = exc
            current = None
        if current is not None and not current.expired(now) and current.holder != self.cfg.node_id:
            self._attach_part(slot, current)
            return
        # no successor yet: go read-only NOW anyway — writes accepted past our
        # deadline could race the successor's promotion (they would die at the
        # fence, but refusing them at the door is cheaper and honest)
        eng = self._engines[slot.pid]
        if self.cfg.link_factory is not None and eng._repl_cfg is not None \
                and self._engine_is_writable(slot.pid):
            try:
                eng.demote(None)
            except MetricsTPUUserError as exc:
                self.last_error = exc
        slot.following = None

    # ------------------------------------------------------------------ following

    def _follow_part(self, now: float, slot: _PartSlot, view: Tuple[str, bool, int]) -> None:
        cfg = self.cfg
        health, bootstrapped, lag_seqs = view
        try:
            lease = self._store.read_lease(slot.name)
        except CoordStoreError as exc:
            self.last_error = exc
            return
        if lease is not None and not lease.expired(now):
            if lease.holder == cfg.node_id:
                # we won the CAS (or a promote retry is pending): finish the job
                slot.lease = lease
                self._try_promote_part(now, slot, lease)
                return
            slot.election_backoff = 0.0
            if self._engine_is_writable(slot.pid) or slot.following != lease.holder:
                # a revived old owner rejoins the new lineage; a follower of a
                # dead owner re-attaches to the new one's link
                self._attach_part(slot, lease)
            return
        # --- no live lease for this partition: election
        if not bootstrapped or health != "SERVING":
            return  # ineligible: never promote a gap/quarantine into leadership
        if now < slot.next_attempt:
            return
        if not self._is_favourite(now, slot, lag_seqs):
            # hold back one jittered round so the healthiest peer usually wins
            # uncontested; the CAS keeps safety if we both try anyway
            slot.election_backoff = min(
                max(slot.election_backoff * 2.0, cfg.election_backoff_s), cfg.backoff_cap_s
            )
            slot.next_attempt = now + self._jitter(slot.election_backoff)
            return
        applier = getattr(self._engines[slot.pid], "_applier", None)
        floor = (int(applier.epoch) + 1) if applier is not None else self._lease_floor(slot)
        floor = max(floor, self.pmap.epoch_floor(slot.pid))
        try:
            won = self._store.acquire_lease(
                cfg.node_id, cfg.lease_ttl_s, epoch_floor=floor, name=slot.name
            )
        except CoordStoreError as exc:
            self.last_error = exc
            return
        if won is None:
            # a real lost election for this partition: another candidate won
            slot.next_attempt = now + self._jitter(cfg.election_backoff_s)
            return
        slot.lease = won
        slot.promote_backoff = 0.0
        self._try_promote_part(now, slot, won)

    def _is_favourite(self, now: float, slot: _PartSlot, my_lag: int) -> bool:
        """Rank THIS partition's candidacy over peers' per-partition records."""
        try:
            members = self._store.members()
        except CoordStoreError:
            return True  # can't rank: let the CAS arbitrate
        mine = (my_lag if my_lag >= 0 else float("inf"), self.cfg.node_id)
        for peer in self.cfg.peers:
            rec = members.get(peer)
            if rec is None or self._confirmed_dead(now, rec):
                continue
            part = (rec.parts or {}).get(slot.name)
            if part is None:
                continue  # peer doesn't supervise this partition
            if (
                part.get("role") == "follower"
                and part.get("bootstrapped")
                and part.get("health", rec.health) == "SERVING"
            ):
                peer_lag = int(part.get("lag", -1))
                peer_rank = (peer_lag if peer_lag >= 0 else float("inf"), rec.node_id)
                if peer_rank < mine:
                    return False
        return True

    # ------------------------------------------------------------------ promotion

    def _try_promote_part(self, now: float, slot: _PartSlot, lease: Lease) -> None:
        eng = self._engines[slot.pid]
        if self._engine_is_writable(slot.pid):
            self._transition(slot, "leader")
            return
        cfg = self.cfg
        ship_cfg = None
        repl_cfg = eng._repl_cfg
        if cfg.link_factory is not None and repl_cfg is not None:
            links = [cfg.link_factory(cfg.node_id, peer, slot.name) for peer in cfg.peers]
            ship_cfg = _dc_replace(
                repl_cfg,
                role="primary",
                transport=FanoutTransport(links),
                epoch=lease.epoch,
            )
        try:
            eng.promote(epoch=lease.epoch, ship=ship_cfg)
        except NotPromotableError as exc:
            # retryable by contract: the bootstrap snapshot has not landed yet.
            # Keep the lease (we renew while retrying) and back off jittered.
            self.last_error = exc
            slot.promote_backoff = min(
                max(slot.promote_backoff * 2.0, cfg.election_backoff_s), cfg.backoff_cap_s
            )
            slot.next_attempt = now + self._jitter(slot.promote_backoff)
            return
        except MetricsTPUUserError as exc:
            # non-retryable refusal: release so a healthier peer can win
            # instead of us wedging the partition
            self.last_error = exc
            slot.lease = None
            try:
                self._store.release_lease(cfg.node_id, name=slot.name)
            except CoordStoreError:
                pass
            return
        slot.failovers += 1
        slot.following = None
        self._transition(slot, "leader")
        _obs.record_part_failover(cfg.node_id, slot.name)

    # ------------------------------------------------------------------ attachment

    def _attach_part(self, slot: _PartSlot, lease: Lease) -> None:
        """(Re)join ``lease.holder``'s lineage for ONE partition, fencing our
        previous inbound link for that partition only."""
        eng = self._engines[slot.pid]
        cfg = self.cfg
        if cfg.link_factory is None or eng._repl_cfg is None:
            # externally wired (or repl-less) topology: role label only
            slot.following = lease.holder
            self._transition(slot, "follower")
            return
        if not self._engine_is_writable(slot.pid) and slot.following == lease.holder:
            return
        old_transport = eng._repl_cfg.transport
        follower_cfg = _dc_replace(
            eng._repl_cfg,
            role="follower",
            transport=cfg.link_factory(lease.holder, cfg.node_id, slot.name),
            epoch=lease.epoch,
        )
        try:
            eng.demote(follower_cfg)
        except MetricsTPUUserError as exc:
            self.last_error = exc
            return
        try:
            # the deposed lineage dies at the boundary FOR THIS PARTITION: late
            # shipments into our old inbound p-link are fenced, not replayed
            old_transport.fence(lease.epoch)
        except Exception as exc:  # noqa: BLE001 — best effort; receive-side checks remain
            self.last_error = exc
        slot.following = lease.holder
        self._transition(slot, "follower")

    # ------------------------------------------------------------------ plumbing

    def _jitter(self, base: float) -> float:
        return base * (1.0 + 0.5 * self._rng.random())

    def _transition(self, slot: _PartSlot, role: str) -> None:
        if role == slot.role:
            return
        old, slot.role = slot.role, role
        _obs.set_part_role(self.cfg.node_id, slot.name, role)
        hook = self.cfg.on_transition
        if hook is not None:
            try:
                hook(slot.name, old, role)
            except Exception:  # noqa: BLE001 — an observer crash must not poison the tick
                pass

    @property
    def failovers(self) -> int:
        return sum(s.failovers for s in self._slots.values())

    @property
    def lease_renewals(self) -> int:
        return sum(s.lease_renewals for s in self._slots.values())

    def health_view(self) -> Dict[str, Any]:
        """Node-local partition-plane state, one plain dict."""
        now = self._store.now()
        parts: Dict[str, Any] = {}
        for pid, slot in sorted(self._slots.items()):
            lease = slot.lease
            parts[slot.name] = {
                "role": slot.role,
                "lease_epoch": lease.epoch if lease is not None else None,
                "lease_ttl_remaining_s": (
                    max(0.0, lease.remaining(now)) if lease is not None else None
                ),
                "following": slot.following,
                "failovers": slot.failovers,
            }
        return {
            "node_id": self.cfg.node_id,
            "partitions": parts,
            "owned": sorted(self.owned()),
            "suspected_peers": sorted(self._suspected),
            "failovers": self.failovers,
            "lease_renewals": self.lease_renewals,
            "suspicions": self.suspicions,
        }

    def tenant_keys(self, pid: int) -> List[Any]:
        """Every tenant partition ``pid``'s engine currently knows (slab +
        tiered) — migration/sweep introspection."""
        eng = self._engines[pid]
        keys = list(eng._keyed.keys)
        tier = getattr(eng, "_tier", None)
        if tier is not None:
            keys.extend(tier.keys())
        return keys
