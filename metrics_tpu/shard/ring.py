"""Consistent-hash ring: deterministic tenant → shard placement with monotone resizes.

Three properties the sharded engine's correctness rides on, each property-tested
(tests/shard/test_ring.py):

- **Deterministic across processes.** Placement must agree between a process and
  its recovered successor (WAL replay routes a tenant to the shard whose journal
  holds it) and between every rank of a multi-host job (cross-shard ``compute_all``
  iterates shards in the same order everywhere). Python's ``hash()`` is
  salted per process (PYTHONHASHSEED), so keys are first serialized to canonical
  bytes (:func:`stable_key_bytes`) and then mixed with the sketch plane's murmur3
  finalizer (:func:`metrics_tpu.sketch.kernels._mix32_py`) — no interpreter state
  anywhere in the path.
- **Balanced.** Each shard owns ``vnodes`` points on a 32-bit ring; a tenant lands
  on the shard owning the first point clockwise of its hash. At the default 256
  vnodes/shard the per-shard load envelope is max/mean ≤ 1.3 for 1k tenants on 8
  shards (the tested envelope; measured ≤ 1.26 across seeds 0–7).
- **Monotone under growth.** Growing N → M shards only *adds* points; a tenant
  either keeps its shard or moves to a NEW one (old shards never trade tenants),
  and each new shard steals ~K/M of K tenants. Doubling therefore relocates the
  minimum possible ~K/2 total, ≲1.3·K/M per new shard — this is what bounds the
  rebalance migration to "what the new capacity must own" instead of a full
  reshuffle.
"""

from __future__ import annotations

import bisect
import pickle
import struct
from typing import Dict, Hashable, List, Sequence, Tuple

from metrics_tpu.sketch.kernels import _GOLD, _mix32_py

DEFAULT_VNODES = 256


def stable_key_bytes(key: Hashable) -> bytes:
    """Canonical byte identity of a tenant key, stable across processes.

    Type-tagged so ``1``, ``1.0``, ``"1"`` and ``b"1"`` stay distinct. Tuples
    recurse with length prefixes; anything else falls back to pickle protocol 2
    (deterministic for the simple immutable types a tenant key should be).
    """
    if isinstance(key, bytes):
        return b"b" + key
    if isinstance(key, str):
        return b"s" + key.encode("utf-8")
    if isinstance(key, bool):  # before int: bool is an int subclass
        return b"o1" if key else b"o0"
    if isinstance(key, int):
        return b"i" + str(key).encode("ascii")
    if isinstance(key, float):
        return b"f" + struct.pack("<d", key)
    if key is None:
        return b"n"
    if isinstance(key, tuple):
        parts = [b"t", struct.pack("<I", len(key))]
        for item in key:
            sub = stable_key_bytes(item)
            parts.append(struct.pack("<I", len(sub)))
            parts.append(sub)
        return b"".join(parts)
    return b"p" + pickle.dumps(key, protocol=2)


def hash_bytes(data: bytes, seed: int = 0) -> int:
    """Well-mixed 32-bit hash of ``data``: 4-byte little-endian chunks folded
    through the murmur3 finalizer, length-finalized (murmur3's tail defense —
    ``b"a"`` and ``b"a\\x00"`` must not collide)."""
    h = _mix32_py(seed ^ _GOLD)
    for i in range(0, len(data), 4):
        h = _mix32_py(h ^ int.from_bytes(data[i : i + 4], "little"))
    return _mix32_py(h ^ len(data))


class HashRing:
    """Immutable consistent-hash ring over ``shards`` shard indices."""

    def __init__(self, shards: int, *, vnodes: int = DEFAULT_VNODES, seed: int = 0) -> None:
        if shards < 1:
            raise ValueError(f"HashRing needs >= 1 shard, got {shards}")
        if vnodes < 1:
            raise ValueError(f"HashRing needs >= 1 vnode per shard, got {vnodes}")
        self.shards = int(shards)
        self.vnodes = int(vnodes)
        self.seed = int(seed)
        points: List[Tuple[int, int]] = []
        for shard in range(self.shards):
            points.extend(self._shard_points(shard))
        # ties (hash collisions between vnodes) resolve by shard index — the
        # sort is total, so every process builds the identical ring
        points.sort()
        self._hashes = [h for h, _ in points]
        self._owners = [s for _, s in points]

    def _shard_points(self, shard: int) -> List[Tuple[int, int]]:
        return [
            (hash_bytes(b"shard:%d:vnode:%d" % (shard, v), seed=self.seed), shard)
            for v in range(self.vnodes)
        ]

    def shard_for(self, key: Hashable) -> int:
        """Owning shard index: first ring point clockwise of the key's hash."""
        h = hash_bytes(stable_key_bytes(key), seed=self.seed)
        i = bisect.bisect_right(self._hashes, h)
        if i == len(self._hashes):
            i = 0  # wrap: past the last point means the lowest point owns it
        return self._owners[i]

    def grown(self, new_shards: int) -> "HashRing":
        """A new ring with ``new_shards`` shards (same vnodes/seed).

        Shards ``0..self.shards-1`` contribute exactly the same points as
        before, so growth is monotone: every key either keeps its owner or
        moves to a shard index ``>= self.shards``.
        """
        if new_shards <= self.shards:
            raise ValueError(
                f"HashRing.grown: new shard count {new_shards} must exceed current {self.shards}"
            )
        return HashRing(new_shards, vnodes=self.vnodes, seed=self.seed)

    def assignment(self, keys: Sequence[Hashable]) -> Dict[Hashable, int]:
        """Bulk ``shard_for`` (property tests and rebalance planning)."""
        return {key: self.shard_for(key) for key in keys}

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, HashRing)
            and (self.shards, self.vnodes, self.seed)
            == (other.shards, other.vnodes, other.seed)
        )

    def __repr__(self) -> str:
        return f"HashRing(shards={self.shards}, vnodes={self.vnodes}, seed={self.seed})"
