"""Shard plane: tenant-sharded serving over the device mesh.

One :class:`~metrics_tpu.engine.StreamingEngine` caps the system at one host's
HBM and one dispatcher thread. This plane consistent-hashes tenants onto N
shards — each a full engine with its own stacked slab, compile cache,
dispatcher, and guard plane — behind one router, with monotone rebalancing on
capacity growth. See docs/source/sharding.md.

    from metrics_tpu.shard import ShardConfig, ShardedEngine

    engine = ShardedEngine(BinaryAccuracy(), config=ShardConfig(shards=8))
    engine.submit("tenant-a", preds, target)
    engine.compute("tenant-a")
"""

from metrics_tpu.shard.engine import ShardConfig, ShardedEngine
from metrics_tpu.shard.ring import DEFAULT_VNODES, HashRing, hash_bytes, stable_key_bytes

__all__ = [
    "DEFAULT_VNODES",
    "HashRing",
    "ShardConfig",
    "ShardedEngine",
    "hash_bytes",
    "stable_key_bytes",
]
