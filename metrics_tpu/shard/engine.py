"""Tenant-sharded serving: N StreamingEngines behind one consistent-hash router.

One :class:`~metrics_tpu.engine.StreamingEngine` owns ALL tenant state — one
host's HBM and one dispatcher thread cap the whole system. :class:`ShardedEngine`
breaks that ceiling the way "Automatic Cross-Replica Sharding of Weight Update"
(PAPERS.md) partitions optimizer work: the *accumulation* state itself is
partitioned. Tenants are consistent-hashed (:mod:`metrics_tpu.shard.ring`) onto
N shards; each shard is a full StreamingEngine with its own stacked
``KeyedState`` slab, bucket-kernel compile cache, dispatcher thread, and guard
plane — so N backlogs drain in parallel and guard policy (token buckets,
quarantine, backpressure) follows the tenant to its shard.

Concurrency contract:

- ``submit`` takes NO global lock. The ring lookup is pure math; the only lock
  on the path is one of ``_STRIPES`` striped locks (chosen by submitter thread
  id — disjoint submitter threads use disjoint locks) plus the target engine's
  own queue lock. A ``resize`` acquires ALL stripes, which is what quiesces
  submits during migration without making them pay for each other in steady
  state.
- Admin operations (``compute`` / ``compute_all`` / ``rotate_window`` /
  ``reset`` / ``resize`` / ``checkpoint_now`` / ``close``) serialize on one
  re-entrant ``_admin_lock``; none of them sits on the submit path.

Device placement: when the process sees >1 JAX device (a real mesh, or the test
suite's ``xla_force_host_platform_device_count`` virtual mesh), shard *i*'s
slab is committed to device ``i % ndevices`` (``StreamingEngine(device=...)``
→ every init leaf is ``device_put`` there, and jit dispatches follow committed
operands), so shards update on distinct devices in parallel. The equivalent
``NamedSharding(Mesh(devices, ("shard",)), PartitionSpec("shard"))`` is exposed
as ``self.sharding`` for introspection; placement itself is per-shard
commitment because each shard's slab is an independent array tree (different
capacities, independent growth), not one global stacked array.

Rebalancing: ``resize(new_shards)`` grows the hash ring monotonically (old
shards never trade tenants — only new shards steal ~K/M each), migrates exactly
the stolen tenants through the PR 4 ckpt snapshot container (bit-identical
round trip, window ring segments included), and evicts them from their old
shard. With checkpointing configured, the migration commits in write-ahead
order: destination shards snapshot their installed copies, THEN the new-count
ring manifest is written, and only then are the source copies evicted (in
memory and via the sources' post-evict snapshots). A crash before the manifest
commit restarts under the old ring with every source copy intact; a crash
after it restarts under the new ring, where the recovery sweep evicts any
tenant found on a shard the ring no longer routes it to (the double copy the
remaining window leaves behind). No ordering leaves a tenant's only copy on a
shard the manifest does not construct.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import threading
from typing import Any, Dict, Hashable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from metrics_tpu.ckpt import format as ckpt_format
from metrics_tpu.engine.runtime import CheckpointConfig, StreamingEngine
from metrics_tpu.engine.stream import EagerKeyedState, KeyedState
from metrics_tpu.obs import context as _obs_ctx
from metrics_tpu.obs import instrument as _obs
from metrics_tpu.obs.registry import OBS as _OBS
from metrics_tpu.shard.ring import DEFAULT_VNODES, HashRing
from metrics_tpu.utils.exceptions import MetricsTPUUserError

_N_STRIPES = 16
_MANIFEST = "shard_manifest.json"

# distinguishes sharded engines within one process for the obs shard series
_SHARDED_IDS = itertools.count()


@dataclasses.dataclass(frozen=True)
class ShardConfig:
    """Shard-plane wiring for one :class:`ShardedEngine`.

    ``shards`` is the initial shard count; ``vnodes``/``seed`` parameterize the
    consistent-hash ring and MUST be stable across restarts of the same
    deployment (the checkpoint manifest enforces this — a changed ring would
    route tenants away from the shard whose WAL holds them). ``place_on_mesh``
    commits shard *i*'s slab to JAX device ``i % ndevices`` when more than one
    device is visible; off, every shard shares the default device (still N
    dispatcher threads, one device).
    """

    shards: int = 2
    vnodes: int = DEFAULT_VNODES
    seed: int = 0
    place_on_mesh: bool = True


class ShardedEngine:
    """Consistent-hash tenant sharding over N parallel :class:`StreamingEngine` shards.

    Same per-tenant semantics as one StreamingEngine — per-tenant results are
    bit-identical to a single-engine oracle for commutative (integer-state)
    metrics under any submit interleaving, and for all metrics when each
    tenant's updates are submitted from one thread (the same sequential-
    semantics contract the unsharded engine documents).

    Example::

        engine = ShardedEngine(BinaryAccuracy(), config=ShardConfig(shards=8))
        engine.submit("tenant-a", preds, target)
        engine.compute("tenant-a")
        engine.resize(16)          # doubling: only new shards steal tenants
        engine.close()
    """

    def __init__(
        self,
        metric_or_collection: Any,
        *,
        config: Optional[ShardConfig] = None,
        checkpoint: Optional[CheckpointConfig] = None,
        start: bool = True,
        **engine_kwargs: Any,
    ) -> None:
        self._config = config or ShardConfig()
        if self._config.shards < 1:
            raise MetricsTPUUserError(
                f"ShardedEngine needs >= 1 shard, got {self._config.shards}"
            )
        self._metric_template = metric_or_collection
        self._engine_kwargs = dict(engine_kwargs)
        self._ckpt_cfg = checkpoint
        self._start = start
        self.engine_id = str(next(_SHARDED_IDS))

        self._ring = HashRing(
            self._config.shards, vnodes=self._config.vnodes, seed=self._config.seed
        )
        # striped submit locks: submit holds ITS thread's stripe; resize holds
        # ALL of them. Stripes are dealt round-robin per submitter thread (raw
        # thread ids are pointer-aligned and would pile onto one stripe), so
        # disjoint submitter threads get disjoint locks and the steady-state
        # cost is one uncontended acquire.
        self._stripes = [threading.Lock() for _ in range(_N_STRIPES)]
        self._stripe_local = threading.local()
        self._stripe_counter = itertools.count()
        # submit-path route memo: ring hashing (stable key encoding + the
        # murmur fold) is pure Python and would dominate a batch-1 submit.
        # One entry per live tenant; cleared under ALL stripes when resize
        # swaps the ring. CPython dict get/set are atomic, so concurrent
        # stripes may share it without their own lock.
        self._route_cache: Dict[Hashable, int] = {}
        self._admin_lock = threading.RLock()
        self._closed = False

        self._devices: List[Any] = []
        self.mesh = None
        self.sharding = None
        if self._config.place_on_mesh:
            devs = jax.devices()
            if len(devs) > 1:
                self._devices = list(devs)
                from jax.sharding import Mesh, NamedSharding, PartitionSpec

                self.mesh = Mesh(np.array(devs), ("shard",))
                self.sharding = NamedSharding(self.mesh, PartitionSpec("shard"))

        if checkpoint is not None:
            self._check_or_write_manifest(checkpoint.directory)

        self._engines: List[StreamingEngine] = [
            self._build_shard(i, start=start) for i in range(self._config.shards)
        ]
        if checkpoint is not None:
            self._recovery_sweep()
        self._publish_tenant_gauges()

    # ------------------------------------------------------------- construction

    def _build_shard(self, index: int, *, start: bool = True) -> StreamingEngine:
        kwargs = dict(self._engine_kwargs)
        kwargs["device"] = (
            self._devices[index % len(self._devices)] if self._devices else None
        )
        kwargs["telemetry_labels"] = {"shard": str(index)}
        # tiered shards spill to per-shard subdirectories: shard indexes are
        # stable across restarts (the manifest pins the ring), so a recovered
        # shard finds exactly its own cold files
        tier_cfg = kwargs.get("tier")
        if tier_cfg is not None and tier_cfg.spill_directory:
            kwargs["tier"] = dataclasses.replace(
                tier_cfg,
                spill_directory=os.path.join(
                    tier_cfg.spill_directory, f"shard-{index:03d}"
                ),
            )
        if self._ckpt_cfg is not None:
            kwargs["checkpoint"] = dataclasses.replace(
                self._ckpt_cfg,
                directory=os.path.join(self._ckpt_cfg.directory, f"shard-{index:03d}"),
            )
        return StreamingEngine(self._metric_template, start=start, **kwargs)

    def _check_or_write_manifest(self, directory: str) -> None:
        """Ring parameters ride in the checkpoint directory: a restart with a
        different ring would route tenants away from the shard whose snapshot/WAL
        holds them, which must be a crash at construction, not silent data loss."""
        path = os.path.join(directory, _MANIFEST)
        want = {
            "shards": self._config.shards,
            "vnodes": self._config.vnodes,
            "seed": self._config.seed,
        }
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as fh:
                have = json.load(fh)
            if (have.get("vnodes"), have.get("seed")) != (want["vnodes"], want["seed"]):
                raise MetricsTPUUserError(
                    f"shard manifest at {path} was written with ring parameters "
                    f"vnodes={have.get('vnodes')}, seed={have.get('seed')} but this "
                    f"engine was configured with vnodes={want['vnodes']}, "
                    f"seed={want['seed']} — a changed ring strands tenants on "
                    "shards the router no longer reaches"
                )
            if int(have.get("shards", 0)) != want["shards"]:
                raise MetricsTPUUserError(
                    f"shard manifest at {path} records {have.get('shards')} shards "
                    f"but this engine was configured with {want['shards']}; resume "
                    "with the recorded count, then resize()"
                )
            return
        self._write_manifest(directory, want)

    @staticmethod
    def _write_manifest(directory: str, manifest: Dict[str, Any]) -> None:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, _MANIFEST)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)

    def _recovery_sweep(self) -> None:
        """Evict recovered tenants from shards the ring does not route them to.

        Two sources: a crash mid-``resize`` after the new-count manifest
        committed but before the sources' post-evict checkpoints did (tenant
        present on BOTH shards — the ring says the destination owns it, so the
        stale source copy must go), and operator error re-homing a checkpoint
        tree. The ring's copy is authoritative; the stale copy is dropped, not
        merged (migration copied the full state, so merging would double-count).
        """
        for index, engine in enumerate(self._engines):
            stale = [
                key
                for key in self._shard_keys(engine)
                if self._ring.shard_for(key) != index
            ]
            for key in stale:
                # journaled retire: releases the slot to the free-list (or drops
                # the tier entry + spill file) and makes the NEXT recovery agree
                engine.evict_tenant(key)

    @staticmethod
    def _shard_keys(engine: StreamingEngine) -> List[Hashable]:
        """Every tenant one shard knows: slab-resident plus warm/cold tiered."""
        keys = list(engine._keyed.keys)
        if engine._tier is not None:
            keys.extend(engine._tier.keys())
        return keys

    # ------------------------------------------------------------------ routing

    @property
    def shards(self) -> int:
        return len(self._engines)

    @property
    def engines(self) -> Tuple[StreamingEngine, ...]:
        """The per-shard engines, in shard-index order (tests/ops introspection)."""
        return tuple(self._engines)

    @property
    def ring(self) -> HashRing:
        return self._ring

    def shard_of(self, key: Hashable) -> int:
        """The shard index the ring currently routes ``key`` to."""
        with self._admin_lock:
            return self._ring.shard_for(key)

    @property
    def keys(self) -> Tuple[Hashable, ...]:
        """Every registered tenant, shard-index order then per-shard insertion order."""
        with self._admin_lock:
            out: List[Hashable] = []
            for engine in self._engines:
                out.extend(self._shard_keys(engine))
            return tuple(out)

    # ------------------------------------------------------------------- writes

    def submit(
        self,
        key: Hashable,
        *args: Any,
        deadline: Optional[float] = None,
        priority: int = 0,
    ) -> Any:
        """Route one update to its tenant's shard; returns that shard's Future.

        The stripe lock pins the ring↔engine pairing against a concurrent
        ``resize`` (which holds every stripe while it migrates); it is NOT a
        global submit lock — submitter threads on different stripes proceed
        concurrently, and the per-shard queues/backpressure they land in are
        independent.
        """
        # mint (or adopt) the trace context HERE so the traced request id is
        # the one the caller saw at the sharded front door, then activate it
        # around the delegated submit: the shard's inner engine adopts the
        # ambient context instead of minting a second, unlinked trace
        ctx = _obs_ctx.mint_or_current() if _OBS.enabled else None
        stripe = getattr(self._stripe_local, "lock", None)
        if stripe is None:
            stripe = self._stripes[next(self._stripe_counter) % _N_STRIPES]
            self._stripe_local.lock = stripe
        with stripe:
            index = self._route_cache.get(key)
            if index is None:
                index = self._ring.shard_for(key)
                self._route_cache[key] = index
            with _obs_ctx.activate(ctx):
                return self._engines[index].submit(
                    key, *args, deadline=deadline, priority=priority
                )

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every accepted request on every shard has committed.

        Serializes with ``resize`` on the admin lock: a flush that overlapped a
        rebalance could otherwise return while newly born shards still held
        unflushed migrated work.
        """
        with self._admin_lock:
            for engine in self._engines:
                engine.flush(timeout=timeout)

    # -------------------------------------------------------------------- reads

    def compute(self, key: Hashable, *, window: bool = False, sync: bool = False) -> Any:
        """Final metric value for tenant ``key`` (flushes its shard first).

        Held under the admin lock end-to-end: a concurrent ``resize`` may move
        the tenant between the ring lookup and the shard read, and computing on
        a shard that just evicted the key would KeyError.
        """
        with self._admin_lock:
            engine = self._engines[self._ring.shard_for(key)]
            return engine.compute(key, window=window, sync=sync)

    def compute_all(self, *, window: bool = False, sync: bool = False) -> Dict[Hashable, Any]:
        """``compute`` for every tenant on every shard.

        Shards are visited in index order — the ring is deterministic across
        processes, so every rank of a multi-host job issues ``sync=True``
        collectives in the same shard order (per-shard tenant order carries the
        same single-writer caveat as the unsharded engine's ``compute_all``).
        Each shard's slice is point-in-time consistent; the union is as
        consistent as N sequential per-shard snapshots can be.
        """
        with self._admin_lock:
            out: Dict[Hashable, Any] = {}
            for engine in self._engines:
                out.update(engine.compute_all(window=window, sync=sync))
            return out

    def register_tenants(self, keys: Sequence[Hashable]) -> int:
        """Register tenants as cold residents on their ring-routed shards.

        Requires the shards to be built with ``tier=TierConfig(...)``. Routes
        each key once and batches per shard; returns how many were new."""
        with self._admin_lock:
            buckets: Dict[int, List[Hashable]] = {}
            for key in keys:
                buckets.setdefault(self._ring.shard_for(key), []).append(key)
            added = 0
            for index, batch in buckets.items():
                added += self._engines[index].register_tenants(batch)
        self._publish_tenant_gauges()
        return added

    def tenant_tier(self, key: Hashable) -> Optional[str]:
        """Which tier ``key`` occupies on its shard (None = unknown tenant)."""
        with self._admin_lock:
            return self._engines[self._ring.shard_for(key)].tenant_tier(key)

    def tier_stats(self) -> Dict[str, Any]:
        """Summed residency counts + slab bytes, with the per-shard stats under
        ``"shards"`` (index order)."""
        with self._admin_lock:
            per_shard = [engine.tier_stats() for engine in self._engines]
        out: Dict[str, Any] = {
            field: sum(stats[field] for stats in per_shard)
            for field in ("hot", "warm", "cold", "pinned", "slab_bytes")
        }
        out["shards"] = per_shard
        return out

    def health(self) -> Dict[str, Any]:
        """Aggregate state (worst shard wins) + the per-shard health dicts."""
        with self._admin_lock:
            per_shard = [engine.health() for engine in self._engines]
            ring_repr = repr(self._ring)
        order = {"SERVING": 0, "DEGRADED": 1, "QUARANTINED": 2}
        worst = max((h["state"] for h in per_shard), key=lambda s: order.get(s, 2))
        return {"state": worst, "shards": per_shard, "ring": ring_repr}

    def telemetry_snapshot(self) -> Dict[str, Any]:
        """Additive sums across shards + the per-shard snapshots (keyed by index).

        Only additive series are summed into the top level: the integer event
        counters and gauges (``processed``, ``queue_depth``, ...) plus the
        ``resize_seconds`` wall-time counter. Non-additive series — latency
        quantiles, occupancy histograms, mean ratios — appear only under the
        per-shard sub-dicts (the sum of eight per-shard p50s is not a p50).
        """
        with self._admin_lock:
            shards = {str(i): e.telemetry.snapshot() for i, e in enumerate(self._engines)}
        totals: Dict[str, Any] = {}
        for snap in shards.values():
            for name, val in snap.items():
                if isinstance(val, bool) or not isinstance(val, (int, float)):
                    continue
                if isinstance(val, int) or name == "resize_seconds":
                    totals[name] = totals.get(name, 0) + val
        totals["shards"] = shards
        return totals

    # ----------------------------------------------------------- admin lifecycle

    def rotate_window(self) -> None:
        """Close the sliding-window segment on EVERY shard.

        One call rotates all shards under the admin lock, so ring segment
        counts stay index-aligned across shards — rebalance migration copies a
        tenant's window contributions segment-by-segment on that alignment.
        """
        with self._admin_lock:
            for engine in self._engines:
                engine.rotate_window()

    def reset(self) -> None:
        with self._admin_lock:
            for engine in self._engines:
                engine.reset()

    def checkpoint_now(self) -> List[Optional[int]]:
        """Synchronous snapshot per shard; the committed generations, index order."""
        with self._admin_lock:
            return [engine.checkpoint_now() for engine in self._engines]

    def close(self, flush: bool = True, checkpoint: bool = True) -> None:
        with self._admin_lock:
            if self._closed:
                return
            self._closed = True
            for engine in self._engines:
                engine.close(flush=flush, checkpoint=checkpoint)

    # -------------------------------------------------------------- rebalancing

    def resize(self, new_shards: int) -> Dict[Hashable, Tuple[int, int]]:
        """Grow to ``new_shards`` shards, migrating only the tenants the ring moves.

        Monotone ring growth means every move goes old-shard → NEW-shard
        (≈K/new_shards stolen per new shard); each moved tenant's state — live
        segment AND window ring rows — round-trips through the PR 4 ckpt
        snapshot container, bit-identically. Submits are quiesced for the
        duration (all stripes held); in-flight work is flushed first so the
        copied state is complete. Returns ``{key: (from_shard, to_shard)}``.

        Crash safety (checkpointing on) is write-ahead ordering: copies are
        installed on the destinations WITHOUT evicting the sources, the
        destination (born) shards checkpoint, the new-count ring manifest
        commits, and only then are the source copies evicted and the sources'
        post-evict checkpoints taken. A crash before the manifest commit
        restarts under the old ring with every source copy intact (the born
        directories hold only stale bytes, dropped by the next resize); a
        crash after it restarts under the new ring, whose recovery sweep
        resolves the double copies in the destination's favor. At no point is
        a tenant's only durable copy on a shard the manifest does not
        construct.
        """
        with self._admin_lock:
            if self._closed:
                raise MetricsTPUUserError("resize() on a closed ShardedEngine")
            if new_shards <= len(self._engines):
                raise MetricsTPUUserError(
                    f"resize() only grows: {new_shards} <= current {len(self._engines)}"
                )
            new_ring = self._ring.grown(new_shards)
            # build the new shards before quiescing submits — the stripe hold
            # should cover migration only, not engine construction. They run
            # (or not) under the same lifecycle flag as the original shards.
            born = [
                self._build_shard(i, start=self._start)
                for i in range(len(self._engines), new_shards)
            ]
            # A born shard may reuse a shard-NNN directory left by a resize
            # that crashed before its manifest committed, and resume=True will
            # have recovered that leftover state. It is stale by construction:
            # the old-count manifest means the original shards recovered every
            # authoritative copy (sources are never durably evicted ahead of
            # the manifest). Drop it all before migration installs fresh
            # copies, or resurrected tenants would duplicate live ones.
            for engine in born:
                for key in self._shard_keys(engine):
                    engine.evict_tenant(key)
            for stripe in self._stripes:
                stripe.acquire()
            try:
                engines = self._engines + born
                # flush under the stripes: after this no shard has queued or
                # in-flight work, so dispatch-lock state reads are complete
                for engine in self._engines:
                    engine.flush()
                moved: Dict[Hashable, Tuple[int, int]] = {}
                for src_idx, src in enumerate(self._engines):
                    # every tenant the shard knows migrates, whatever tier it
                    # occupies: hot rows copy from the slab, warm/cold entries
                    # copy without readmission (no slab churn during a resize)
                    for key in self._shard_keys(src):
                        dst_idx = new_ring.shard_for(key)
                        if dst_idx == src_idx:
                            continue
                        self._copy_tenant(src, engines[dst_idx], key)
                        moved[key] = (src_idx, dst_idx)
                if self._ckpt_cfg is not None:
                    # destination durability, then the ring that routes to it,
                    # then source eviction — see the docstring's crash argument
                    if any(engine.checkpoint_now() is None for engine in born):
                        for engine in born:
                            engine.close(flush=False, checkpoint=False)
                        raise RuntimeError(
                            "resize() aborted: a destination shard failed to "
                            "checkpoint its migrated tenants; the old ring and "
                            "every source copy are intact"
                        )
                    try:
                        self._write_manifest(
                            self._ckpt_cfg.directory,
                            {
                                "shards": new_shards,
                                "vnodes": self._config.vnodes,
                                "seed": self._config.seed,
                            },
                        )
                    except BaseException:
                        # abort pre-commit: the old ring and every source copy
                        # are untouched; only the born engines need unwinding
                        for engine in born:
                            engine.close(flush=False, checkpoint=False)
                        raise
                for key, (src_idx, _) in moved.items():
                    self._engines[src_idx].evict_tenant(key)
                if self._ckpt_cfg is not None:
                    for engine in self._engines:
                        engine.checkpoint_now()
                self._engines = engines
                self._ring = new_ring
                self._route_cache.clear()
                self._config = dataclasses.replace(self._config, shards=new_shards)
            finally:
                for stripe in self._stripes:
                    stripe.release()
        _obs.record_shard_rebalance(self.engine_id)
        self._publish_tenant_gauges()
        return moved

    def _copy_tenant(self, src: StreamingEngine, dst: StreamingEngine, key: Hashable) -> None:
        """Copy one tenant src → dst, bit-identically, through the ckpt container.

        The source copy is left in place (``retire=False``): ``resize`` evicts
        it only once the destination copy and the ring routing to it are both
        durable. The engine-level export/import pair handles every tier — a
        warm or cold tenant migrates without ever touching either slab, and a
        registered-but-silent one moves as a cold registration.
        """
        entry = src.export_tenant(key, retire=False)
        blob = ckpt_format.dumps(entry)
        dst.import_tenant(key, ckpt_format.loads(blob).tree)

    @staticmethod
    def _export_tenant(keyed: Any, key: Hashable) -> Dict[str, Any]:
        """One tenant's full state as a host tree: live segment + window ring rows
        (``None`` where the tenant had no contribution in a segment)."""
        state = jax.device_get(keyed.state_of(key))
        ring_rows: List[Any] = []
        if isinstance(keyed, KeyedState):
            slot = keyed._slots[key]
            if keyed._ring is not None:
                for cap, snap in keyed._ring:
                    if slot >= cap:
                        ring_rows.append(None)
                    else:
                        ring_rows.append(
                            jax.device_get(jax.tree.map(lambda x: x[slot], snap))
                        )
        else:
            if keyed._ring is not None:
                for seg in keyed._ring:
                    row = seg.get(key)
                    ring_rows.append(None if row is None else jax.device_get(row))
        return {"state": state, "ring": ring_rows}

    @staticmethod
    def _install_tenant(keyed: Any, key: Hashable, tree: Dict[str, Any]) -> None:
        keyed.slot_for(key)
        keyed.ensure_capacity()
        keyed.set_state(key, tree["state"])
        rows = tree.get("ring") or []
        if not rows:
            return
        if isinstance(keyed, KeyedState):
            slot = keyed._slots[key]
            ring = keyed._ring
            if ring is None:
                return
            # segments align by index across shards: every rotation goes
            # through ShardedEngine.rotate_window, which rotates all shards —
            # except a shard born mid-life, whose ring starts empty and is
            # padded with init segments here so the alignment holds
            while len(ring) < len(rows):
                ring.append((keyed.capacity, keyed._tiled(keyed.capacity)))
            for j, row in enumerate(rows):
                if row is None or j >= len(ring):
                    continue
                cap, snap = ring[j]
                if slot >= cap:
                    # the destination snapshot predates this slot: grow it so
                    # the migrated contribution has a row to land in
                    leaves, treedef = jax.tree_util.tree_flatten(snap)
                    grown = [
                        jax.numpy.concatenate(
                            [
                                leaf,
                                jax.numpy.broadcast_to(
                                    init, (keyed.capacity - cap,) + init.shape
                                ),
                            ],
                            axis=0,
                        )
                        for leaf, init in zip(leaves, keyed._init_leaves)
                    ]
                    snap = jax.tree_util.tree_unflatten(treedef, grown)
                    cap = keyed.capacity
                snap = jax.tree.map(
                    lambda s, r: s.at[slot].set(jax.numpy.asarray(r)), snap, row
                )
                ring[j] = (cap, snap)
        else:
            ring = keyed._ring
            if ring is None:
                return
            while len(ring) < len(rows):
                ring.append({})
            for j, row in enumerate(rows):
                if row is None or j >= len(ring):
                    continue
                ring[j][key] = row

    # ---------------------------------------------------------------------- obs

    def _publish_tenant_gauges(self) -> None:
        for index, engine in enumerate(self._engines):
            _obs.set_shard_tenants(
                self.engine_id, index, len(self._shard_keys(engine))
            )

    def publish_tenant_gauges(self) -> None:
        """Refresh ``metrics_tpu_shard_tenants`` from the live slot maps (obs-gated)."""
        self._publish_tenant_gauges()
