"""CoDel-style overload shedding on queue sojourn time.

Queue *length* is the wrong overload signal for a micro-batching engine — a
deep queue that drains in one dispatch is healthy. Sojourn time (how long the
oldest work has actually waited) is the signal CoDel built on, and the same
two-phase logic applies here, adapted from per-packet dequeue to per-drain
batches:

- **standing overload detection**: the controller tracks the *minimum* sojourn
  seen at each drain. A single slow drain (a compile, a capacity growth) spikes
  sojourn transiently; only a minimum that stays above ``target_s`` for a full
  ``interval_s`` is standing overload.
- **escalating shed**: once in the dropping state, each further overloaded
  drain sheds one more request than the last (1, 2, 3, …) until the minimum
  sojourn falls back under target, which exits the state and resets the
  escalation. Victims are chosen by the caller (the guard plane sheds the
  oldest low-priority requests — they have already blown the target).

Deterministic: all time flows through the injected clock; tests drive the
state machine directly with a :class:`~metrics_tpu.guard.faults.ManualClock`.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

__all__ = ["CoDelShedder"]


class CoDelShedder:
    """Two-state (normal → dropping) sojourn-time controller."""

    def __init__(
        self,
        target_s: float = 0.1,
        interval_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.target_s = float(target_s)
        self.interval_s = float(interval_s)
        self._clock = clock
        self._first_above: Optional[float] = None  # when the interval timer expires
        self.dropping = False
        self.drop_count = 0

    def on_drain(self, min_sojourn_s: float, now: Optional[float] = None) -> int:
        """One drain observed ``min_sojourn_s``; returns how many requests to shed."""
        now = self._clock() if now is None else now
        if min_sojourn_s < self.target_s:
            # recovered: leave dropping, forget the interval timer and escalation
            self._first_above = None
            self.dropping = False
            self.drop_count = 0
            return 0
        if self._first_above is None:
            self._first_above = now + self.interval_s
            return 0
        if not self.dropping and now < self._first_above:
            return 0  # above target, but not yet for a full interval
        self.dropping = True
        self.drop_count += 1
        return self.drop_count
