"""Fault doubles for guard-plane testing: deterministic clocks, wedged and
killed dispatchers, poison request generators.

Complements the other planes' injectors (``comm.transport`` Flaky/Stall/
DeadPeer, ``ckpt.faults`` tear/flip_bit/DiskFull) with the failure modes the
guard plane exists to survive; ``tools/fuzz_soak.py --surfaces guard``
composes all three families against one engine.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Iterator, Optional, Tuple

__all__ = [
    "ManualClock",
    "hold_dispatch_lock",
    "kill_dispatcher",
    "poison_args",
    "wedge_dispatcher",
]


class ManualClock:
    """A monotonic clock tests advance by hand — the zero-sleep time source
    every guard policy accepts via ``GuardConfig(clock=...)``."""

    def __init__(self, start: float = 0.0) -> None:
        self._now = float(start)
        self._lock = threading.Lock()

    def __call__(self) -> float:
        with self._lock:
            return self._now

    def advance(self, dt: float) -> float:
        with self._lock:
            self._now += float(dt)
            return self._now

    def set(self, t: float) -> float:
        with self._lock:
            self._now = float(t)
            return self._now


@contextmanager
def wedge_dispatcher(engine: Any) -> Iterator[None]:
    """Wedge the dispatcher *between* drain and processing (gate hook): the
    worker sits on a drained batch without holding the dispatch lock — the
    recoverable hang (watchdog replays inline and restarts). The gate reopens
    on exit so the superseded worker can observe its stale epoch and retire."""
    engine._worker_gate.clear()
    try:
        yield
    finally:
        engine._worker_gate.set()


@contextmanager
def hold_dispatch_lock(engine: Any) -> Iterator[None]:
    """Simulate a worker wedged *inside* a device call: the dispatch lock is
    held and cannot be taken over — the unrecoverable hang (engine
    quarantines itself rather than risk double-commit)."""
    engine._dispatch_lock.acquire()
    try:
        yield
    finally:
        engine._dispatch_lock.release()


def kill_dispatcher(engine: Any, exc: Optional[BaseException] = None) -> BaseException:
    """Arm a one-shot dispatcher crash: the next drained batch raises ``exc``
    inside the worker, triggering the worker-death ladder (inline replay, and
    a guard-managed restart when configured). Returns the armed exception."""
    boom = exc if exc is not None else RuntimeError("guard.faults: injected dispatcher crash")
    original = engine._process

    def exploding(batch: Any, *args: Any, **kwargs: Any) -> Any:
        engine._process = original  # one-shot: the replay/restart path runs clean
        raise boom

    engine._process = exploding
    return boom


def poison_args(rows: int = 2) -> Tuple[Any, Any]:
    """Arguments that pass admission (consistent leading axis) but fail inside
    any two-argument elementwise update: incompatible trailing shapes."""
    import numpy as np

    return np.zeros((rows, 3), np.float32), np.zeros((rows, 4), np.float32)
