"""Poison-tenant quarantine: per-tenant breaker over request *failures*.

A tenant whose requests repeatedly fail (malformed shapes, dtype garbage —
anything that raises inside its own update) costs more than its own futures:
on the fused path each poison chunk pays a failed trace plus the eager retry.
After ``threshold`` consecutive failures the tenant is quarantined: its
submits fail fast with :class:`~metrics_tpu.guard.errors.TenantQuarantined`
(state untouched, no retry cost) until a probation expires; then exactly one
probe request is admitted. A successful probe clears the tenant entirely; a
failed probe re-quarantines with probation grown by ``factor`` (capped), so a
persistently poisonous tenant converges to ~zero amortized cost.

Only *processing* failures count — quota/backpressure/deadline rejections
never touch the ledger (being rate-limited is not being poisonous). Memory is
bounded: only tenants with a live failure streak have an entry, and any
success deletes it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Hashable, Optional

__all__ = ["ALLOW", "DENY", "PROBE", "TenantQuarantine"]

ALLOW, PROBE, DENY = "allow", "probe", "deny"


class _Entry:
    __slots__ = ("consecutive", "offenses", "quarantined_until", "probing", "held")

    def __init__(self) -> None:
        self.consecutive = 0
        self.offenses = 0  # quarantines served without an intervening success
        self.quarantined_until: Optional[float] = None
        self.probing = False
        self.held = False  # administrative hold (migration guard): only release() clears


class TenantQuarantine:
    def __init__(
        self,
        *,
        threshold: int = 5,
        probation_s: float = 1.0,
        probation_max_s: float = 300.0,
        probation_factor: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.threshold = int(threshold)
        self.probation_s = float(probation_s)
        self.probation_max_s = float(probation_max_s)
        self.probation_factor = float(probation_factor)
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: Dict[Hashable, _Entry] = {}

    def _probation(self, offenses: int) -> float:
        return min(
            self.probation_max_s,
            self.probation_s * self.probation_factor ** max(0, offenses - 1),
        )

    def check(self, key: Hashable) -> str:
        """Admission verdict for one submit: ALLOW, PROBE (admitted as the
        single half-open probe), or DENY (probation still running)."""
        if not self._entries:
            # hot path: no tenant has a live failure streak — one dict-empty
            # test, no lock (a racing first failure is seen on the next submit)
            return ALLOW
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or entry.quarantined_until is None:
                return ALLOW
            if self._clock() < entry.quarantined_until:
                return DENY
            if entry.probing:
                return DENY  # one probe at a time
            entry.probing = True
            return PROBE

    def record(self, key: Hashable, ok: bool) -> bool:
        """Record one processed request's outcome. Returns True if this
        failure (newly) quarantined the tenant."""
        if ok and not self._entries:
            return False  # hot path: nothing to forgive, no lock
        with self._lock:
            if ok:
                held = self._entries.get(key)
                if held is not None and held.held:
                    held.consecutive = 0  # a straggler's success never lifts a hold
                    return False
                self._entries.pop(key, None)  # forgiveness resets the ladder
                return False
            entry = self._entries.setdefault(key, _Entry())
            if entry.held:
                return False  # the hold already denies harder than any breaker would
            entry.consecutive += 1
            failed_probe = entry.probing
            entry.probing = False
            if failed_probe or entry.consecutive >= self.threshold:
                entry.offenses += 1
                entry.quarantined_until = self._clock() + self._probation(entry.offenses)
                entry.consecutive = 0
                return True
            return False

    def hold(self, key: Hashable) -> None:
        """Administratively quarantine ``key`` until :meth:`release` — no
        probation expiry, no probe. The partition plane holds a tenant on its
        migration *source* so stale-routed writes refuse loudly instead of
        silently re-creating evicted state at init."""
        with self._lock:
            entry = self._entries.setdefault(key, _Entry())
            entry.held = True
            entry.quarantined_until = float("inf")
            entry.probing = False

    def release(self, key: Hashable) -> None:
        """Lift an administrative hold (no-op for breaker-owned entries)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry.held:
                del self._entries[key]

    def abandon(self, key: Hashable) -> None:
        """The admitted probe never ran (e.g. the submit was rejected further
        down the pipeline) — free the probe slot so the tenant is not wedged."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry.probing = False

    def is_held(self, key: Hashable) -> bool:
        """True while an administrative :meth:`hold` is in force for ``key``.

        The migration drain barrier's second gate: a submit that passed
        admission BEFORE the hold landed re-checks here under the engine
        lock, so no row can slip in behind the source drain."""
        if not self._entries:
            return False  # same lock-free hot path as check()
        with self._lock:
            entry = self._entries.get(key)
            return entry is not None and entry.held

    def is_quarantined(self, key: Hashable) -> bool:
        with self._lock:
            entry = self._entries.get(key)
            return (
                entry is not None
                and entry.quarantined_until is not None
                and self._clock() < entry.quarantined_until
            )

    def active(self) -> Dict[Hashable, float]:
        """Currently-quarantined tenants → probation expiry (clock units)."""
        now = self._clock()
        with self._lock:
            return {
                key: entry.quarantined_until
                for key, entry in self._entries.items()
                if entry.quarantined_until is not None and now < entry.quarantined_until
            }
