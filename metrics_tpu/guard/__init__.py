"""metrics_tpu.guard — admission control, overload shedding, circuit breakers
and the dispatch watchdog for the serving stack.

The engine's correctness planes (comm retry/degradation, ckpt crash recovery)
keep *faults* from becoming wrong answers; the guard plane keeps *overload and
abuse* from becoming everyone's latency. Wire it in with one argument::

    from metrics_tpu.engine import StreamingEngine
    from metrics_tpu.guard import GuardConfig

    engine = StreamingEngine(
        metric,
        guard=GuardConfig(
            quota_rows_per_s=10_000,     # per-tenant token-bucket admission
            watchdog_timeout_s=30.0,     # hung-dispatcher detection + restart
        ),
    )
    fut = engine.submit(key, preds, target, deadline=0.5, priority=1)
    engine.health()   # {"state": "SERVING", "breakers": {...}, ...}

Five mechanisms, one config (see :class:`~metrics_tpu.guard.config.GuardConfig`
and docs/source/robustness.md):

1. per-tenant token-bucket quotas + weighted fair micro-batch formation
   (fairness enforced at drain time, not just admission);
2. request deadlines + CoDel-style sojourn-time load shedding;
3. circuit breakers with half-open probes around kernel compiles, checkpoint
   commits, and comm sync;
4. poison-tenant quarantine with exponential probation;
5. a dispatch watchdog driving the SERVING → DEGRADED → QUARANTINED health
   state machine (``engine.health()`` + master-gated obs gauges).

Every policy takes an injectable clock (deterministic tests, no sleeps);
fault doubles live in :mod:`metrics_tpu.guard.faults`.
"""

from metrics_tpu.guard.breaker import BREAKER_STATE_CODES, CircuitBreaker, CompileGovernor
from metrics_tpu.guard.config import GuardConfig
from metrics_tpu.guard.errors import (
    DeadlineExceeded,
    EngineQuarantined,
    GuardRejected,
    QuotaExceeded,
    RequestShed,
    TenantQuarantined,
)
from metrics_tpu.guard.fairness import FairBacklog, FifoBacklog, fair_order
from metrics_tpu.guard.plane import GuardPlane
from metrics_tpu.guard.quarantine import TenantQuarantine
from metrics_tpu.guard.quota import TenantQuotas, TokenBucket
from metrics_tpu.guard.shed import CoDelShedder
from metrics_tpu.guard.watchdog import HangDetector, Watchdog

__all__ = [
    "BREAKER_STATE_CODES",
    "CircuitBreaker",
    "CoDelShedder",
    "CompileGovernor",
    "DeadlineExceeded",
    "EngineQuarantined",
    "FairBacklog",
    "fair_order",
    "FifoBacklog",
    "GuardConfig",
    "GuardPlane",
    "GuardRejected",
    "HangDetector",
    "QuotaExceeded",
    "RequestShed",
    "TenantQuarantine",
    "TenantQuotas",
    "TokenBucket",
    "Watchdog",
]
