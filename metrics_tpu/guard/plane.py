"""GuardPlane — the one object the engine talks to; composes every policy.

Admission (``admit``) runs on the caller's thread at ``submit`` entry; drain
forming (``form_drain``) runs on the dispatcher between queue and kernels;
breaker gates wrap the three failure-prone dependencies (kernel compiles,
checkpoint commits, comm sync); outcome recording (``on_request_outcome``)
feeds the poison-tenant quarantine. Every decision is counted twice: in the
engine's always-on telemetry (closed counter set, flat snapshot) and — when
``obs`` is enabled — in the master-gated ``metrics_tpu_guard_*`` series.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List, Optional, Tuple

from metrics_tpu.guard.breaker import (
    BREAKER_STATE_CODES,
    CircuitBreaker,
    CompileGovernor,
)
from metrics_tpu.guard.config import GuardConfig
from metrics_tpu.guard.errors import (
    DeadlineExceeded,
    QuotaExceeded,
    RequestShed,
    TenantQuarantined,
)
from metrics_tpu.guard.fairness import FairBacklog, FifoBacklog
from metrics_tpu.guard.quarantine import DENY, PROBE, TenantQuarantine
from metrics_tpu.guard.quota import TenantQuotas
from metrics_tpu.guard.shed import CoDelShedder
from metrics_tpu.obs import instrument as _obs

__all__ = ["GuardPlane"]


class GuardPlane:
    def __init__(self, cfg: GuardConfig, *, telemetry: Any, max_rows: int) -> None:
        self.cfg = cfg
        self.clock = cfg.clock
        self._telemetry = telemetry
        self._engine_label = getattr(telemetry, "engine_id", "0")
        self.quotas = TenantQuotas(
            cfg.quota_rows_per_s, cfg.quota_burst_rows, cfg.tenant_quotas, cfg.clock
        )
        self.shedder = (
            CoDelShedder(cfg.shed_target_s, cfg.shed_interval_s, cfg.clock) if cfg.shed else None
        )
        self.quarantine = TenantQuarantine(
            threshold=cfg.quarantine_threshold,
            probation_s=cfg.quarantine_probation_s,
            probation_max_s=cfg.quarantine_probation_max_s,
            probation_factor=cfg.quarantine_probation_factor,
            clock=cfg.clock,
        )

        def _breaker(name: str) -> CircuitBreaker:
            return CircuitBreaker(
                name,
                failure_threshold=cfg.breaker_failure_threshold,
                probation_s=cfg.breaker_probation_s,
                probation_max_s=cfg.breaker_probation_max_s,
                probation_factor=cfg.breaker_probation_factor,
                clock=cfg.clock,
                on_transition=self._on_breaker_transition,
            )

        self.compile_governor = (
            CompileGovernor(cfg.compile_rate_per_s, cfg.compile_burst, _breaker("compile"))
            if cfg.compile_breaker
            else None
        )
        self.ckpt_breaker = _breaker("ckpt") if cfg.ckpt_breaker else None
        self.comm_breaker = _breaker("comm") if cfg.comm_breaker else None
        # default quantum: 8 bucket-maxima of rows per dispatch cycle — deep
        # enough that healthy traffic drains in one fast-path cycle (per-cycle
        # fixed costs stay off the <5% overhead gate), shallow enough that a
        # flood's current cycle bounds everyone else's wait; latency-sensitive
        # deployments tune it down (see benchmarks/engine_throughput.py --guard)
        self.drain_quantum = (
            cfg.drain_quantum_rows if cfg.drain_quantum_rows is not None else 8 * int(max_rows)
        )
        # the persistent fair backlog: drained requests live HERE (per-tenant
        # deques, weighted-DRR selection), not in the engine's arrival-order
        # queue — selection is O(selected + tenants) per drain regardless of
        # how deep a flooding tenant's backlog grows
        self.backlog = (
            FairBacklog(cfg.tenant_weights, self.drain_quantum)
            if cfg.fair
            else FifoBacklog(self.drain_quantum)
        )
        # submit stamps t_enqueue only when sojourn-time shedding will read it
        self.stamp_enqueue = self.shedder is not None
        # hot-path elision flags (read inline by the engine so a guarded submit
        # with nothing to check costs attribute loads, not calls): full
        # admission runs only when quotas are configured, a deadline was
        # passed, or some tenant has a live failure ledger entry
        self.admission_active = self.quotas.enabled
        self._quarantine_entries = self.quarantine._entries  # same dict object

    # ------------------------------------------------------------------ accounting

    def _count(self, name: str, obs_kind: Optional[str] = None, n: int = 1) -> None:
        self._telemetry.count(name, n)
        if obs_kind is not None:
            _obs.record_guard_event(self._engine_label, obs_kind, n)

    def _on_breaker_transition(self, name: str, old: str, new: str) -> None:
        _obs.set_guard_breaker_state(self._engine_label, name, BREAKER_STATE_CODES[new])

    # ------------------------------------------------------------------ admission

    def admit(self, key: Hashable, rows: int, deadline: Optional[float]) -> Tuple[Optional[float], bool]:
        """Admission checks for one submit; returns ``(abs_deadline, is_probe)``.

        Raises :class:`TenantQuarantined` / :class:`QuotaExceeded` /
        :class:`DeadlineExceeded` (an already-expired deadline never enters the
        queue). A rejected submit consumes no quota tokens.
        """
        verdict = self.quarantine.check(key)
        if verdict == DENY:
            self._count("quarantine_rejections")
            raise TenantQuarantined(
                f"tenant {key!r} is quarantined after repeated request failures; "
                "it fails fast until its probation expires"
            )
        is_probe = verdict == PROBE
        try:
            if deadline is not None and deadline <= 0:
                self._count("deadline_expired", "deadline_expired")
                raise DeadlineExceeded(f"deadline {deadline}s already expired at submit")
            if self.quotas.enabled and not self.quotas.admit(key, rows):
                self._count("quota_rejections", "quota_rejections")
                raise QuotaExceeded(
                    f"tenant {key!r} exceeded its admission quota ({rows} rows refused)"
                )
        except Exception:
            if is_probe:
                self.quarantine.abandon(key)
            raise
        abs_deadline = None if deadline is None else self.clock() + float(deadline)
        return abs_deadline, is_probe

    def abandon_probe(self, key: Hashable) -> None:
        self.quarantine.abandon(key)

    # ------------------------------------------------------------------ drain forming

    def form_drain(
        self, new_requests: List[Any], now: Optional[float] = None
    ) -> Tuple[List[Any], List[Tuple[Any, Exception]]]:
        """Ingest newly drained requests, then form one dispatch batch.

        Returns ``(batch, rejected)``: ``batch`` to dispatch now (fair
        interleave, ≤ drain quantum rows), ``rejected`` as ``(request,
        exception)`` pairs to fail fast (expired deadlines, shed overload
        victims). The un-selected remainder stays in :attr:`backlog` — the
        engine never sees or rescans it, so the per-drain cost is bounded by
        the quantum, not the flood.
        """
        backlog = self.backlog
        shedder = self.shedder
        to_shed: Optional[int] = None  # None = sojourn not yet observed this drain
        # no-backlog fast path: with nothing standing, arrivals that fit the
        # quantum (and carry no deadline) dispatch as-is — no per-tenant queue
        # churn. This is the common healthy regime and what keeps the guard's
        # well-behaved overhead (<5% gate) down in the many-small-drains case.
        if not backlog.count and new_requests:
            total = 0
            any_deadline = False
            for req in new_requests:
                total += int(req.rows)
                if req.deadline is not None:
                    any_deadline = True
            if total <= self.drain_quantum and not any_deadline:
                if shedder is None:
                    return list(new_requests), []
                now = self.clock() if now is None else now
                to_shed = shedder.on_drain(now - new_requests[-1].t_enqueue, now=now)
                if not to_shed:
                    return list(new_requests), []

        backlog.ingest(new_requests)
        rejected: List[Tuple[Any, Exception]] = []

        if shedder is not None and backlog.count:
            now = self.clock() if now is None else now
            newest = backlog.newest_enqueue()
            min_sojourn = 0.0 if newest is None else now - newest
            if to_shed is None:
                to_shed = shedder.on_drain(min_sojourn, now=now)
            if to_shed:
                victims = backlog.shed_oldest(self.cfg.shed_max_priority, to_shed)
                if victims:
                    self._count("shed", "shed", len(victims))
                    for req in victims:
                        self._release_if_probe(req)
                        rejected.append(
                            (req, RequestShed(
                                f"shed under overload (queue sojourn {min_sojourn:.3f}s "
                                f"above target {self.shedder.target_s}s)"
                            ))
                        )

        # deadlines expire lazily, as requests reach selection: an expired
        # request never occupies a batch slot, and the clock is only read if
        # some request actually carries a deadline
        deadline_now = now

        def _expired(req: Any) -> bool:
            nonlocal deadline_now
            if req.deadline is None:
                return False
            if deadline_now is None:
                deadline_now = self.clock()
            return deadline_now >= req.deadline

        batch, expired = backlog.select(reject=_expired)
        if expired:
            self._count("deadline_expired", "deadline_expired", len(expired))
            for req in expired:
                self._release_if_probe(req)
                rejected.append(
                    (req, DeadlineExceeded(f"deadline expired in queue for tenant {req.key!r}"))
                )
        return batch, rejected

    def _release_if_probe(self, req: Any) -> None:
        """A quarantine probe rejected in-queue (shed, expired, failed fast by a
        takeover) never ran — free its probe slot or the tenant is wedged in
        DENY forever (the probation already lapsed, so only the probe flag
        stands between it and re-admission)."""
        if getattr(req, "is_probe", False):
            self.quarantine.abandon(req.key)

    def take_backlog(self) -> List[Any]:
        """Hand every backlogged request to a death/hang takeover replay."""
        return self.backlog.take_all()

    # ------------------------------------------------------------------ outcomes & breakers

    def on_request_outcome(self, key: Hashable, ok: bool) -> None:
        if self.quarantine.record(key, ok):
            self._count("quarantines", "quarantines")

    def allow_compile(self) -> bool:
        if self.compile_governor is None:
            return True
        if self.compile_governor.allow_compile():
            return True
        self._count("compile_rejections")
        return False

    def breaker_snapshots(self) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        if self.compile_governor is not None:
            out["compile"] = self.compile_governor.breaker.snapshot()
        if self.ckpt_breaker is not None:
            out["ckpt"] = self.ckpt_breaker.snapshot()
        if self.comm_breaker is not None:
            out["comm"] = self.comm_breaker.snapshot()
        return out

    def breakers_open(self) -> List[str]:
        return [name for name, snap in self.breaker_snapshots().items() if snap["state"] != "closed"]

    @property
    def shedding(self) -> bool:
        return self.shedder is not None and self.shedder.dropping

    def publish_health(self, state: str) -> None:
        _obs.set_guard_health(self._engine_label, state)
