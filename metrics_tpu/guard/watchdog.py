"""Dispatch watchdog: detect a hung worker and hand the decision to the engine.

Split for testability:

- :class:`HangDetector` is the pure policy — "has the dispatcher been busy on
  one batch longer than ``timeout_s``?" — fed by ``busy_since`` marks from the
  engine and read with an injected clock, so every detection scenario is a
  deterministic unit test.
- :class:`Watchdog` is the thin monitor thread: poll the probe, fire
  ``on_hang`` once per detection (the engine's hang handler supersedes the
  worker, so the same hang never fires twice), swallow nothing silently — a
  probe/handler crash is recorded on ``last_error``.

What "hung" means and what happens next (the lock-probe split between inline
replay + restart vs engine quarantine) is the engine's call — see
``StreamingEngine._on_worker_hang`` and docs/source/robustness.md.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

__all__ = ["HangDetector", "Watchdog"]


class HangDetector:
    """Busy-too-long policy over engine-provided marks (injectable clock)."""

    def __init__(self, timeout_s: float, clock: Callable[[], float] = time.monotonic) -> None:
        self.timeout_s = float(timeout_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._busy_since: Optional[float] = None

    def mark_busy(self) -> None:
        """The dispatcher took ownership of a batch (called at drain)."""
        with self._lock:
            if self._busy_since is None:
                self._busy_since = self._clock()

    def mark_idle(self) -> None:
        """The batch (and its follow-up work) finished."""
        with self._lock:
            self._busy_since = None

    def hung(self, now: Optional[float] = None) -> bool:
        with self._lock:
            if self._busy_since is None:
                return False
            return ((self._clock() if now is None else now) - self._busy_since) > self.timeout_s


class Watchdog:
    """Daemon thread: ``probe()`` every ``poll_s``; fire ``on_hang()`` on True."""

    def __init__(
        self,
        probe: Callable[[], bool],
        on_hang: Callable[[], None],
        *,
        poll_s: float = 0.05,
        name: str = "metrics-tpu-guard-watchdog",
    ) -> None:
        self._probe = probe
        self._on_hang = on_hang
        self._poll_s = float(poll_s)
        self._stop = threading.Event()
        self.last_error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            try:
                if self._probe():
                    self._on_hang()
            except Exception as exc:  # noqa: BLE001 — the monitor must outlive its probe
                self.last_error = exc

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=timeout)
