"""Circuit breakers with half-open probes and exponential probation.

The classic three-state machine::

                 failure_threshold consecutive failures
        CLOSED ────────────────────────────────────────► OPEN
          ▲                                               │ probation elapses
          │ probe succeeds                                ▼
          └────────────────────────────────────────── HALF_OPEN
                                                          │ probe fails
                                                          └──► OPEN (probation × factor)

- ``permit()`` is the gate callers check before touching the protected
  dependency. CLOSED always permits; OPEN permits nothing until the probation
  expires, at which point the breaker moves to HALF_OPEN and permits exactly
  ONE probe; further ``permit()`` calls are refused until that probe resolves
  via ``record_success``/``record_failure`` (or ``abandon_probe`` if the
  caller never actually attempted it).
- probation grows exponentially with consecutive trips —
  ``probation_s × factor^(trips-1)``, capped at ``probation_max_s`` — and a
  recorded success resets both the failure streak and the trip ladder.

:class:`CompileGovernor` specialises the breaker for the kernel-compile
dependency: the *failure* there is not an exception but an exhausted compile
budget (a token bucket on compile-cache misses). While the budget holds,
compiles pass and the breaker stays closed; a miss with an empty bucket counts
as a failure, and a tripped breaker routes novel-signature chunks to eager
execution until a half-open probe finds budget again.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from metrics_tpu.guard.quota import TokenBucket

__all__ = ["BREAKER_STATE_CODES", "CircuitBreaker", "CompileGovernor"]

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"

# gauge encoding for metrics_tpu_guard_breaker_state (docs/source/robustness.md)
BREAKER_STATE_CODES: Dict[str, int] = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Thread-safe breaker; all timing through the injected clock."""

    def __init__(
        self,
        name: str,
        *,
        failure_threshold: int = 3,
        probation_s: float = 1.0,
        probation_max_s: float = 60.0,
        probation_factor: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str, str, str], None]] = None,
    ) -> None:
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.probation_s = float(probation_s)
        self.probation_max_s = float(probation_max_s)
        self.probation_factor = float(probation_factor)
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._trips = 0  # consecutive trips without an intervening success
        self._open_until = 0.0
        self._probe_inflight = False

    # ------------------------------------------------------------------ internals

    def _probation(self) -> float:
        return min(
            self.probation_max_s,
            self.probation_s * self.probation_factor ** max(0, self._trips - 1),
        )

    def _transition(self, new: str) -> None:
        old, self._state = self._state, new
        if old != new and self._on_transition is not None:
            try:
                self._on_transition(self.name, old, new)
            except Exception:  # noqa: BLE001 — observability must not break the policy
                pass

    # ------------------------------------------------------------------ public API

    @property
    def state(self) -> str:
        with self._lock:
            if self._state == OPEN and self._clock() >= self._open_until:
                return HALF_OPEN  # what permit() would find
            return self._state

    def permit(self) -> bool:
        """May the caller touch the protected dependency right now?"""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() < self._open_until:
                    return False
                self._transition(HALF_OPEN)
                self._probe_inflight = True
                return True
            # HALF_OPEN: exactly one probe at a time
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def abandon_probe(self) -> None:
        """The permitted probe was never actually attempted — free the slot."""
        with self._lock:
            self._probe_inflight = False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._trips = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            now = self._clock()
            if self._state == HALF_OPEN or (self._state == OPEN and now >= self._open_until):
                # failed probe (or failure observed right as probation lapsed)
                self._trips += 1
                self._probe_inflight = False
                self._open_until = now + self._probation()
                self._transition(OPEN)
                return
            if self._state == OPEN:
                return  # already open; the probation clock is authoritative
            self._consecutive += 1
            if self._consecutive >= self.failure_threshold:
                self._trips += 1
                self._open_until = now + self._probation()
                self._transition(OPEN)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            state = self._state
            if state == OPEN and self._clock() >= self._open_until:
                state = HALF_OPEN
            return {
                "state": state,
                "consecutive_failures": self._consecutive,
                "trips": self._trips,
                "open_until": self._open_until if state == OPEN else None,
            }


class CompileGovernor:
    """Token-bucket compile budget behind a :class:`CircuitBreaker`.

    ``allow_compile()`` is consulted on every kernel-cache miss. Within budget
    the compile proceeds (and closes the breaker). Past budget the miss is a
    breaker failure; once tripped, every novel signature is refused for the
    probation — the caller routes those chunks to eager execution, so a tenant
    spraying novel shapes pays with its own latency instead of everyone's
    compile storms. Cached kernels are never governed (no miss, no check).
    """

    def __init__(self, rate_per_s: float, burst: float, breaker: CircuitBreaker) -> None:
        self.breaker = breaker
        self.bucket = TokenBucket(rate_per_s, burst, breaker._clock)

    def allow_compile(self) -> bool:
        if not self.breaker.permit():
            return False
        if self.bucket.try_take(1.0):
            self.breaker.record_success()
            return True
        self.breaker.record_failure()
        return False
