"""Guard-plane rejections: every fail-fast path gets a distinct, catchable type.

All of these are *admission* or *policy* outcomes, not bugs: the engine is
telling the caller "not this request, not now" in bounded time instead of
letting overload turn into unbounded latency. They subclass
:class:`~metrics_tpu.utils.exceptions.MetricsTPUUserError` so a catch-all for
library-user errors keeps working, with :class:`GuardRejected` as the common
base for "the guard plane refused this request".
"""

from __future__ import annotations

from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = [
    "DeadlineExceeded",
    "EngineQuarantined",
    "GuardRejected",
    "QuotaExceeded",
    "RequestShed",
    "TenantQuarantined",
]


class GuardRejected(MetricsTPUUserError):
    """Base: the guard plane refused this request (fail-fast, state untouched)."""


class QuotaExceeded(GuardRejected):
    """The tenant's token bucket is empty — it exceeded its admitted row rate."""


class DeadlineExceeded(GuardRejected):
    """The request's deadline expired while it waited in the queue (or had
    already expired at submit) — failed fast without occupying a batch slot."""


class RequestShed(GuardRejected):
    """Dropped by the overload controller: queue sojourn time stayed above
    target for a full interval, and this request's priority made it sheddable."""


class TenantQuarantined(GuardRejected):
    """The tenant's requests failed repeatedly; it is serving a probation
    period and fails fast instead of paying the per-request retry cost."""


class EngineQuarantined(GuardRejected):
    """The engine itself cannot serve safely (a dispatch worker is hung inside
    a device call and cannot be superseded) — requests fail fast instead of
    hanging the caller."""
