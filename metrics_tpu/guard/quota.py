"""Token-bucket admission quotas, per tenant.

A bucket holds up to ``burst`` tokens and refills at ``rate`` tokens/second
(continuously, from the injected clock — no refill thread). Admission takes
``rows`` tokens or fails; a failed take does not consume anything, so a tenant
over its rate degrades to exactly its sustained share instead of starving
itself further. ``rate=0`` blocks a tenant outright; ``rate=None`` (no quota
configured) admits everything.

:class:`TenantQuotas` maps tenants to buckets lazily — the set of tenants is
bounded by the engine's key capacity, so the map is too.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Hashable, Optional

__all__ = ["TenantQuotas", "TokenBucket"]


class TokenBucket:
    """Continuous-refill token bucket (thread-safe, injectable clock)."""

    def __init__(self, rate: float, burst: float, clock: Callable[[], float]) -> None:
        if rate < 0 or burst <= 0:
            raise ValueError(f"need rate >= 0 and burst > 0, got rate={rate} burst={burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._stamp = clock()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        elapsed = now - self._stamp
        if elapsed > 0:
            self._tokens = min(self.burst, self._tokens + elapsed * self.rate)
        self._stamp = now

    def try_take(self, n: float = 1.0) -> bool:
        """Take ``n`` tokens if available; a refused take consumes nothing."""
        with self._lock:
            self._refill(self._clock())
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def available(self) -> float:
        with self._lock:
            self._refill(self._clock())
            return self._tokens


class TenantQuotas:
    """Per-tenant token buckets with a default rate and per-tenant overrides."""

    def __init__(
        self,
        rows_per_s: Optional[float],
        burst_rows: Optional[float],
        overrides: Dict[Hashable, float],
        clock: Callable[[], float],
    ) -> None:
        self._rate = rows_per_s
        self._burst = burst_rows
        self._overrides = dict(overrides)
        self._clock = clock
        self._buckets: Dict[Hashable, TokenBucket] = {}
        self._lock = threading.Lock()
        # precomputed: quotas off must cost one attribute read on the submit hot path
        self.enabled = rows_per_s is not None or bool(self._overrides)

    def _bucket(self, key: Hashable) -> Optional[TokenBucket]:
        rate = self._overrides.get(key, self._rate)
        if rate is None:
            return None
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                # burst defaults to 2 seconds of rate (min 1 so a single-row
                # request is always *possible* under a tiny but nonzero rate)
                burst = self._burst if self._burst is not None else max(1.0, 2.0 * rate)
                bucket = self._buckets[key] = TokenBucket(rate, burst, self._clock)
            return bucket

    def admit(self, key: Hashable, rows: int) -> bool:
        """True if tenant ``key`` may submit ``rows`` more rows right now."""
        if not self.enabled:
            return True
        rate = self._overrides.get(key, self._rate)
        if rate is not None and rate <= 0 and self._burst is None:
            # rate 0 blocks outright — no initial-burst freebie. An EXPLICIT
            # burst with rate 0 is the other documented shape: a fixed
            # non-replenishing allowance.
            return False
        bucket = self._bucket(key)
        return True if bucket is None else bucket.try_take(float(rows))
